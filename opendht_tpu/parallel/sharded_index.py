"""Mesh-sharded twin of the device PHT index.

:class:`~opendht_tpu.models.index.DeviceIndex` drives the trie through
the generic batched get/put surface, so the sharded twin only rebinds
those two engine ops onto the routed mesh formulations
(:func:`~opendht_tpu.parallel.sharded_storage.sharded_get` /
:func:`~opendht_tpu.parallel.sharded_storage.sharded_announce`): the
trie encoding, the leaf walk, splits and range scans are byte-for-byte
the same code — host, single-chip and mesh views of one stored trie.

Probe/put batches are already padded to power-of-two widths ≥ 16 by the
base engine, so every batch divides the (≤ 8-way) mesh; capacity-bound
``all_to_all`` drops behave exactly as on the storage path — a dropped
canary/entry replica costs replication for the round and heals on the
next maintenance sweep.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from ..models.index import DeviceIndex, IndexSpec
from ..models.storage import StoreConfig, SwarmStore
from ..models.swarm import Swarm, SwarmConfig
from .sharded_storage import sharded_announce, sharded_get


class ShardedDeviceIndex(DeviceIndex):
    """The device PHT engine with its get/put ops routed over the
    1-D swarm mesh (node-sharded store + routed lookups)."""

    def __init__(self, swarm: Swarm, cfg: SwarmConfig,
                 store: SwarmStore, scfg: StoreConfig, spec: IndexSpec,
                 mesh: Mesh, capacity_factor: float = 4.0,
                 seed: int = 0):
        super().__init__(swarm, cfg, store, scfg, spec, seed=seed)
        self.mesh = mesh
        self.capacity_factor = capacity_factor

    def _get_raw(self, keys: jax.Array):
        res = sharded_get(self.swarm, self.cfg, self.store, self.scfg,
                          keys, self._next_key(), self.mesh,
                          self.capacity_factor)
        return res.hit, res.val, res.payload

    def _put_raw(self, keys, vals, seqs, payloads) -> None:
        self.store, _rep = sharded_announce(
            self.swarm, self.cfg, self.store, self.scfg, keys, vals,
            seqs, 0, self._next_key(), self.mesh,
            capacity_factor=self.capacity_factor, payloads=payloads)
