"""Mesh-sharded batched Kademlia lookups (shard_map + all_to_all).

Two scaling modes over the 1-D ``"swarm"`` mesh axis:

``data_parallel_lookup``
    Node state replicated, lookup batch sharded.  XLA compiles the
    plain :func:`opendht_tpu.models.swarm.lookup` SPMD with zero
    communication — right whenever the swarm fits one chip's HBM.

``sharded_lookup``
    Routing tables sharded on the node axis (tables are the
    HBM-dominant tensor: ``N·B·K·4`` bytes — ~7.7 GB for the 10M-node
    north star, vs 200 MB for ids).  Each lock-step round, every
    device routes its α solicitations to the owning shard with a
    fixed-capacity ``all_to_all`` shuffle, owners gather their local
    bucket rows, and a second ``all_to_all`` returns the responses —
    the in-memory equivalent of the reference's per-packet UDP
    exchange (``NetworkEngine::send``/``processMessage``,
    src/network_engine.cpp:615-632,365-450), ridden over ICI instead.

Both run unmodified on the driver's virtual CPU mesh.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.swarm import (
    LOOKUP_HEADROOM_BYTES,
    LookupFaults,
    LookupResult,
    LookupState,
    LookupTrace,
    Swarm,
    SwarmConfig,
    _finalize,
    _gather_span,
    _ladder_width,
    _local_respond,
    _pending_and_wneed,
    _permute_state,
    _respond,
    _sample_origins,
    _scatter_rows,
    _select_alpha,
    _censor_convicted,
    _select_pair_window,
    _stable_done_perm,
    _unpack_pair_window,
    burst_schedule,
    byz_colluder_pool,
    chaos_step_impl,
    device_hbm_bytes,
    empty_lookup_trace,
    init_impl,
    init_lifecycle,
    lookup,
    resolve_merge_impl,
    run_burst_loop,
    step_impl,
    table_bytes,
)
from ..ops.xor_metric import (
    merge_ladder_widths,
    pick_merge_width,
    prefix_len32,
)
from ..utils.hostdevice import dev_i32
from .mesh import AXIS, shard_map


def data_parallel_lookup(swarm: Swarm, cfg: SwarmConfig,
                         targets: jax.Array, key: jax.Array,
                         mesh: Mesh) -> LookupResult:
    """Lookup batch sharded over the mesh; node state replicated.

    Runs UNCOMPACTED: the local engine's repack is a global row
    permutation, which GSPMD would lower to cross-device shuffles of
    the batch-sharded state (and ladder widths need not divide the
    mesh) — the compacted form of this mode is the table-sharded
    engine's per-shard ladder (:func:`sharded_lookup`)."""
    rep = NamedSharding(mesh, P())
    shd = NamedSharding(mesh, P(AXIS, None))
    swarm = jax.device_put(swarm, rep)
    targets = jax.device_put(targets, shd)
    return lookup(swarm, cfg, targets, key, compact=False)


# ---------------------------------------------------------------------------
# table-sharded mode
# ---------------------------------------------------------------------------

def _shard_positions(owner: jax.Array, ok: jax.Array,
                     n_shards: int) -> jax.Array:
    """Position of each query within its owner shard's capacity bucket.

    Only real queries count — masked rows (-1) clip to node 0 and
    would otherwise inflate shard 0's positions past capacity,
    permanently starving genuine shard-0 traffic.
    """
    onehot = (owner[:, None] == jnp.arange(n_shards)[None, :]) \
        & ok[:, None]
    return jnp.take_along_axis(
        jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1,
        owner[:, None], axis=1)[:, 0]


def _bucketize(owner: jax.Array, ok: jax.Array, n_shards: int,
               cap: int):
    """Sort-based capacity bucketing (no scatters).

    The round-5 decomposition measured the ENTIRE sharded-path
    overhead in the routing machinery (+75 % over the loop structure;
    capacity rule free) — dominated by the scatter into the capacity
    buckets and the 2-D fancy gather back, both of which run on the
    TPU's slow per-element paths.  This formulation uses only the ops
    measured fast on this hardware: one stable ``[Q]`` key sort groups
    requests by owner (stability preserves arrival order, so positions
    are IDENTICAL to the cumsum scheme), bucket bounds come from a
    [D+1] searchsorted, slots fill by contiguous row GATHER from the
    sorted order, and one more scalar sort unsorts the ranks.

    Returns ``(src [D, cap] int32, pos [Q] int32, sent [Q] bool)`` —
    ``src`` is the request index filling each bucket slot (-1 empty);
    callers build the shuffle buffer as ``payload[src]`` (a whole-row
    gather) and recover responses with the flat slot index
    ``owner·cap + pos``.
    """
    q = owner.shape[0]
    okey = jnp.where(ok, owner, n_shards).astype(jnp.int32)
    req = jnp.arange(q, dtype=jnp.int32)
    s_okey, s_req = jax.lax.sort((okey, req), dimension=0, num_keys=1,
                                 is_stable=True)
    bounds = jnp.searchsorted(
        s_okey, jnp.arange(n_shards + 1, dtype=jnp.int32), side="left"
    ).astype(jnp.int32)                                   # [D+1]
    start, end = bounds[:-1], bounds[1:]
    grid = start[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
    valid = grid < jnp.minimum(end, start + cap)[:, None]
    src = jnp.where(valid, s_req[jnp.clip(grid, 0, max(q - 1, 0))], -1)
    rank_sorted = req - start[jnp.clip(s_okey, 0, n_shards - 1)]
    _, pos = jax.lax.sort((s_req, rank_sorted), dimension=0,
                          num_keys=1, is_stable=True)
    sent = ok & (pos < cap)
    return src, pos, sent


def _fill_buckets(payload: jax.Array, src: jax.Array, n_shards: int,
                  cap: int, fill) -> jax.Array:
    """Build the ``[D, cap, W]`` shuffle buffer from ``_bucketize``'s
    slot sources by whole-row gather (empty slots read ``fill``)."""
    q = payload.shape[0]
    srcf = jnp.clip(src.reshape(-1), 0, max(q - 1, 0))
    return jnp.where((src >= 0).reshape(-1, 1), payload[srcf],
                     fill).reshape(n_shards, cap, payload.shape[1])


def _route_respond(tables_local: jax.Array, ids: jax.Array,
                   alive: jax.Array, targets: jax.Array, nid: jax.Array,
                   nid_d0: jax.Array, cfg: SwarmConfig, n_shards: int,
                   capacity_factor: float, cap_nq: int | None = None):
    """Answer solicitations whose routing tables live on other shards.

    ``nid``: ``[Ll, A]`` global node indices (-1 = none); ``nid_d0``
    their first-limb XOR distance to the target (from the shortlist
    state — no id gather).  Returns ``(resp [Ll, A*2K], resp_d0
    [Ll, A*2K], answered [Ll, A])``.  Queries ship ``(local_row,
    bucket)`` to the owner shard in fixed-capacity buckets
    of ``C = capacity_factor · Q/D`` (expected load per shard times
    head-room — NOT the worst-case Q, which would inflate shuffle
    traffic D×), are answered by local gathers of the index + member-
    limb rows, and ship back — two ``all_to_all`` per round,
    O(α·L/D·c) payload each.  Queries landing past an owner's capacity
    are *dropped* this round (``answered`` False): the origin keeps
    them unqueried and re-sends next round, the lock-step analogue of
    the reference's request retransmit after timeout (request.h:113).
    """
    n = cfg.n_nodes
    shard_n = n // n_shards
    ll, a = nid.shape
    q = ll * a
    # ``cap_nq`` pins the query count the capacity rule is provisioned
    # for (default: this call's own Ll·A).  The compaction ladder
    # dispatches rounds on truncated row prefixes, but the transport's
    # per-shard capacity is a property of the PROVISIONED batch, not of
    # the dispatch width — shrinking cap with the prefix would both
    # change drop patterns (breaking the compacted↔uncompacted
    # seed-identity) and mismodel the hardware.
    nq = q if cap_nq is None else cap_nq
    if math.isfinite(capacity_factor):
        cap = min(nq, max(a, int(math.ceil(nq / n_shards
                                           * capacity_factor))))
    else:
        cap = nq
    flat = nid.reshape(-1)
    safe = jnp.clip(flat, 0, n - 1)
    ok = (flat >= 0) & alive[safe]

    # Bucket index from the solicited node's own shortlist distance:
    # c = clz(d0) = commonBits(node, target), exact for n_buckets ≤ 32.
    c = prefix_len32(nid_d0.reshape(-1))
    c0 = jnp.clip(c, 0, cfg.n_buckets - 1)
    c1 = jnp.clip(c + 1, 0, cfg.n_buckets - 1)

    owner = (safe // shard_n).astype(jnp.int32)
    owner = jnp.clip(owner, 0, n_shards - 1)
    local_row = safe - owner * shard_n
    local_row = jnp.where(ok, local_row, -1)

    # One stacked [D, C, 2] shuffle instead of three collectives: the
    # per-collective launch latency sits on the lock-step critical
    # path.  Buckets fill by sort + row gather (see ``_bucketize``).
    # RIGHT-SIZED (round 18): only ``(local_row, c0)`` ship — the
    # second bucket index is always the adjacent one, so the owner
    # derives ``c1 = min(c0+1, B-1)`` locally instead of paying a
    # third shuffle column for a value one add reproduces (1/3 of the
    # query-leg bytes and of the ``_fill_buckets`` gather width, the
    # +51.9 % routed-overhead satellite's first finding).
    src, pos, sent = _bucketize(owner, ok, n_shards, cap)
    pay = jnp.stack([local_row, c0], axis=-1)              # [Q,2]
    qbuf = _fill_buckets(pay, src, n_shards, cap, -1)

    a2a = partial(jax.lax.all_to_all, axis_name=AXIS, split_axis=0,
                  concat_axis=0, tiled=True)
    rbuf = a2a(qbuf)
    slot = owner * cap + jnp.clip(pos, 0, cap - 1)         # [Q]
    r_row, r_c0 = rbuf[..., 0], rbuf[..., 1]
    r_c0 = jnp.clip(r_c0, 0, cfg.n_buckets - 1)
    r_c1 = jnp.clip(r_c0 + 1, 0, cfg.n_buckets - 1)

    # Owner-side fetch of the two bucket rows.  Augmented tables: one
    # whole-row gather per query (the only fast gather over a big
    # table — see the Swarm docstring) + a B-way static-slice window
    # select; the [.., lo K | hi K | s16 K] u16 windows ship back —
    # HALF the return bytes of round 3's exact-limb i32 rows.  Plain
    # tables (fallback) span-gather and get the member limbs from an
    # owner-side id gather — the id matrix is replicated, so it stays
    # local.
    k = cfg.bucket_k
    safe_row = jnp.clip(r_row, 0, shard_n - 1)
    if tables_local.dtype == jnp.uint16:                     # augmented
        # Same whole-row fetch + adjacent-pair select as the local
        # engine (clip to B-2: at the deepest bucket rows B-2 and B-1
        # come back, a candidate superset — identical semantics).
        w3 = 3 * k
        rows = tables_local[safe_row.reshape(-1)]    # [D*C, row_w]
        r_c0p = jnp.clip(r_c0, 0, cfg.n_buckets - 2).reshape(-1)
        resp6 = _select_pair_window(rows, r_c0p, w3, cfg.n_buckets)
        # SLIM return leg (round 20, ROADMAP #1 follow-up): the s16
        # window thirds never ship — they are a gather into the
        # REPLICATED id matrix, so the origin rebuilds them from the
        # decoded indices with the table builder's exact formula
        # (:func:`_rebuild_pair_window`), bit-identical by
        # construction.  [lo K | hi K] per half-row: 4K of the 6K
        # columns ride, −33 % response-leg bytes.
        resp = jnp.concatenate([resp6[:, :2 * k],
                                resp6[:, w3:w3 + 2 * k]],
                               axis=-1).reshape(n_shards, cap, 4 * k)
        resp = jnp.where((r_row >= 0)[..., None], resp,
                         jnp.uint16(0xFFFF))
        back = a2a(resp)                                     # [D,C,4K]
        mine = back.reshape(n_shards * cap, -1)[slot]        # [Q,4K]
        # Window start = the pair start the owner selected — the
        # origin applies the identical clip to its own c0, so no need
        # to ship it back.
        w0 = jnp.clip(c0, 0, cfg.n_buckets - 2)
        t0 = jnp.repeat(targets[:, 0], a)                    # [Q]
        win = _rebuild_pair_window(mine, w0, ids, n, k)
        r_idx, r_d0 = _unpack_pair_window(
            win, w0, w0 + 1, t0, nid_d0.reshape(-1), sent, k)
        return (r_idx.reshape(ll, a * 2 * k),
                r_d0.reshape(ll, a * 2 * k), sent.reshape(ll, a))
    rows0 = _gather_span(tables_local, safe_row, r_c0 * k, k)
    rows1 = _gather_span(tables_local, safe_row, r_c1 * k, k)
    # SLIM return leg (round 20): only the member INDICES ship back —
    # the member limb used to ride as an owner-side id gather, but
    # the id matrix is replicated, so the origin gathers it locally
    # from the same indices (identical values, half the bytes).
    resp = jnp.concatenate([rows0, rows1], axis=-1)          # [D,C,2K]
    resp = jnp.where((r_row >= 0)[..., None], resp, -1)

    back = a2a(resp)                                         # [D,C,2K]
    mine = back.reshape(n_shards * cap, -1)[slot]            # [Q,2K]
    mine = jnp.where(sent[:, None], mine, -1)
    r_idx = mine.reshape(ll, a * 2 * k)
    r_m0 = ids[:, 0][jnp.clip(mine, 0, n - 1)] \
        .reshape(ll, a * 2 * k)
    r_d0 = r_m0 ^ targets[:, 0][:, None]
    r_d0 = jnp.where(r_idx < 0, jnp.uint32(0xFFFFFFFF), r_d0)
    return r_idx, r_d0, sent.reshape(ll, a)


def _rebuild_pair_window(mine: jax.Array, w0: jax.Array,
                         ids: jax.Array, n: int, k: int) -> jax.Array:
    """Rebuild the ``[Q,6K]`` augmented pair window from its slimmed
    ``[Q,4K]`` wire form (``[lo0 K | hi0 K | lo1 K | hi1 K]``).

    Each half-row's s16 third is recomputed with the table builder's
    exact formula ``((m0 << b) >> 16)`` (models/swarm._build_bucket)
    at window start ``w0 + r`` — for occupied slots ``m0`` is the
    SAME replicated-id gather the builder did, so the rebuilt window
    is bit-identical to the stored one; for empty slots the builder
    itself stored the index-0 clip garbage this reproduces, and
    capacity-dropped rows decode to index −1 whose s16 is masked by
    ``_unpack_pair_window``'s validity anyway."""
    halves = []
    for r in range(2):
        lo = mine[:, r * 2 * k:r * 2 * k + k].astype(jnp.uint32)
        hi = mine[:, r * 2 * k + k:r * 2 * k + 2 * k] \
            .astype(jnp.uint32)
        idx = jax.lax.bitcast_convert_type(
            lo | (hi << jnp.uint32(16)), jnp.int32)
        m0 = ids[:, 0][jnp.clip(idx, 0, n - 1)]
        wu = (w0 + r).astype(jnp.uint32)[:, None]
        s16 = ((m0 << wu) >> jnp.uint32(16)).astype(jnp.uint16)
        halves.append(jnp.concatenate(
            [mine[:, r * 2 * k:r * 2 * k + 2 * k], s16], axis=-1))
    return jnp.concatenate(halves, axis=-1)


def _make_responders(cfg: SwarmConfig, n_shards: int,
                     capacity_factor: float, local_respond: bool,
                     ids, tables_local, alive,
                     cap_nq: int | None = None):
    """``(respond_init, respond)`` pair shared by the while-loop and
    burst formulations (ONE copy of the respond contract).

    The init seed is never re-sent — a capacity drop there would leave
    the lookup with an empty shortlist → instant exhaustion-done with
    nothing found — and it is a one-off α=1 exchange, so init runs
    uncapped.  ``local_respond`` (1-device measurement aid for the
    overhead decomposition, BASELINE.md) answers with the local
    engine's gathers instead of the routed exchange.
    """
    if local_respond:
        assert n_shards == 1, "local_respond is a 1-device measurement aid"
        sw = Swarm(ids=ids, tables=tables_local, alive=alive)
        r = lambda tg, nid, d0: _respond(sw, cfg, tg, nid, d0)
        return r, r
    respond = lambda tg, nid, d0: _route_respond(
        tables_local, ids, alive, tg, nid, d0, cfg, n_shards,
        capacity_factor, cap_nq=cap_nq)
    respond_init = lambda tg, nid, d0: _route_respond(
        tables_local, ids, alive, tg, nid, d0, cfg, n_shards,
        float("inf"))
    return respond_init, respond


def _sharded_body(cfg: SwarmConfig, n_shards: int,
                  capacity_factor: float, ids, tables_local,
                  alive, targets, key, local_respond: bool = False):
    """Runs per-device under shard_map: full lookup loop with routed
    responses.  Collective-synchronised while-loop (every shard decides
    from the global not-done count)."""
    ll = targets.shape[0]
    me = jax.lax.axis_index(AXIS)
    key = jax.random.fold_in(key, me)
    origins = _sample_origins(key, alive, ll)

    respond_init, respond = _make_responders(
        cfg, n_shards, capacity_factor, local_respond, ids,
        tables_local, alive)

    # Init: origin's own table answers first (hop 0).  The lock-step
    # round logic is the single shared implementation from
    # models.swarm; only ``respond`` differs between modes.
    st = init_impl(ids, respond_init, cfg, targets, origins)

    def cond(carry):
        st, it = carry
        pending = jax.lax.psum(jnp.sum(~st.done), AXIS)
        return (pending > 0) & (it < cfg.max_steps)

    def body(carry):
        st, it = carry
        return step_impl(ids, alive, respond, cfg, st), it + 1

    st, _ = jax.lax.while_loop(cond, body, (st, jnp.int32(0)))
    return _finalize(ids, st, cfg), st.hops, st.done


@partial(jax.jit, static_argnames=("cfg", "mesh", "capacity_factor",
                                   "local_respond"))
def _sharded_lookup_while(swarm: Swarm, cfg: SwarmConfig,
                          targets: jax.Array, key: jax.Array, mesh: Mesh,
                          capacity_factor: float = 2.0,
                          local_respond: bool = False) -> LookupResult:
    """While-loop formulation: ONE program, convergence checked with an
    on-device psum every round — measured 18 % faster than host bursts
    at 1M nodes (no dispatch gaps, no overshoot rounds).  The loop
    carries the captured table through its carry, and the runtime does
    no input-output aliasing, so peak HBM is ~2× the table — only
    usable while that fits (the dispatcher below decides)."""
    n_shards = mesh.shape[AXIS]
    fn = shard_map(
        partial(_sharded_body, cfg, n_shards, capacity_factor,
                local_respond=local_respond),
        mesh=mesh,
        in_specs=(P(), P(AXIS, None), P(), P(AXIS, None), P()),
        out_specs=(P(AXIS, None), P(AXIS), P(AXIS)),
        check_vma=False,
    )
    found, hops, done = fn(swarm.ids, swarm.tables, swarm.alive, targets,
                           key)
    return LookupResult(found=found, hops=hops, done=done)


def _make_respond_body(cfg, n_shards, capacity_factor, local_respond,
                       init, cap_nq=None, with_rnd=False,
                       merge_w=None):
    """Single-round shard_map bodies for the burst path (same respond
    contract as the while formulation via ``_make_responders``).
    ``cap_nq`` pins capacity provisioning to the full batch width for
    compaction-truncated dispatches (see ``_route_respond``).
    ``with_rnd`` adds the round index as a replicated argument — only
    lifecycle-tracked states need it (``_merge_round``'s
    ``completed_round`` stamp), so untracked programs stay
    byte-identical.  ``merge_w`` is the static merge-width rung
    (guarded in-jit — see ``rank_merge_round_d0_w``); ``None`` keeps
    the exact pre-ladder program.  The init body takes an optional
    per-row ``skip`` mask (``_sharded_lookup_init_masked``): skipped
    rows' origins are blanked to −1 so they never enter the routed
    seed exchange — how cache hits stay OFF the ``all_to_all``.  The
    origin draw stays FULL-width and runs BEFORE the blanking, so
    non-skipped rows' origins are bit-identical to the unmasked
    body's."""
    def init_body(ids, tables_local, alive, targets, key, skip=None):
        ll = targets.shape[0]
        me = jax.lax.axis_index(AXIS)
        key = jax.random.fold_in(key, me)
        origins = _sample_origins(key, alive, ll)
        if skip is not None:
            origins = jnp.where(skip, -1, origins)
        respond_init, _ = _make_responders(
            cfg, n_shards, capacity_factor, local_respond, ids,
            tables_local, alive)
        return init_impl(ids, respond_init, cfg, targets, origins)

    def step_body(ids, tables_local, alive, st):
        _, respond = _make_responders(
            cfg, n_shards, capacity_factor, local_respond, ids,
            tables_local, alive, cap_nq=cap_nq)
        return step_impl(ids, alive, respond, cfg, st,
                         merge_w=merge_w)

    def step_body_rnd(ids, tables_local, alive, st, rnd):
        _, respond = _make_responders(
            cfg, n_shards, capacity_factor, local_respond, ids,
            tables_local, alive, cap_nq=cap_nq)
        return step_impl(ids, alive, respond, cfg, st, rnd=rnd,
                         merge_w=merge_w)

    if init:
        return init_body
    return step_body_rnd if with_rnd else step_body


def _st_specs(track: bool = False):
    """Per-field partition specs for a LookupState.  ``track`` adds the
    lifecycle rows (sharded on the lookup axis like ``done``); without
    it the lifecycle positions are ``None``, matching the empty pytree
    slots of an untracked state."""
    lif = P(AXIS) if track else None
    return LookupState(targets=P(AXIS, None), idx=P(AXIS, None),
                       dist=P(AXIS, None), queried=P(AXIS, None),
                       done=P(AXIS), hops=P(AXIS),
                       admitted_round=lif, completed_round=lif)


@partial(jax.jit, static_argnames=("cfg", "mesh", "capacity_factor",
                                   "local_respond"))
def _sharded_lookup_init(swarm, cfg, targets, key, mesh,
                         capacity_factor, local_respond=False):
    n_shards = mesh.shape[AXIS]
    fn = shard_map(
        _make_respond_body(cfg, n_shards, capacity_factor,
                           local_respond, init=True),
        mesh=mesh,
        in_specs=(P(), P(AXIS, None), P(), P(AXIS, None), P()),
        out_specs=_st_specs(), check_vma=False)
    return fn(swarm.ids, swarm.tables, swarm.alive, targets, key)


@partial(jax.jit, static_argnames=("cfg", "mesh", "capacity_factor",
                                   "local_respond", "cap_nq",
                                   "merge_w"),
         donate_argnums=(2,))
def _sharded_lookup_step(swarm, cfg, st, mesh, capacity_factor,
                         local_respond=False, cap_nq=None, rnd=None,
                         merge_w=None):
    n_shards = mesh.shape[AXIS]
    track = st.admitted_round is not None
    with_rnd = rnd is not None
    body = _make_respond_body(cfg, n_shards, capacity_factor,
                              local_respond, init=False, cap_nq=cap_nq,
                              with_rnd=with_rnd, merge_w=merge_w)
    in_specs = (P(), P(AXIS, None), P(), _st_specs(track))
    args = (swarm.ids, swarm.tables, swarm.alive, st)
    if with_rnd:
        in_specs = in_specs + (P(),)
        args = args + (rnd,)
    fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=_st_specs(track), check_vma=False)
    return fn(*args)


@partial(jax.jit, static_argnames=("cfg", "mesh", "capacity_factor",
                                   "local_respond"))
def _sharded_lookup_init_masked(swarm, cfg, targets, key, skip, mesh,
                                capacity_factor,
                                local_respond=False):
    """Routed init with a per-row ``skip`` mask (cache-aware sharded
    admission, round 20): skipped rows never solicit, so they never
    ride the ``all_to_all`` — non-skipped rows are bit-identical to
    :func:`_sharded_lookup_init` (asserted in tests)."""
    n_shards = mesh.shape[AXIS]
    fn = shard_map(
        _make_respond_body(cfg, n_shards, capacity_factor,
                           local_respond, init=True),
        mesh=mesh,
        in_specs=(P(), P(AXIS, None), P(), P(AXIS, None), P(),
                  P(AXIS)),
        out_specs=_st_specs(), check_vma=False)
    return fn(swarm.ids, swarm.tables, swarm.alive, targets, key,
              skip)


def _resident_rounds_body(cfg, n_shards, capacity_factor, rounds):
    """Per-shard body of the sharded resident round loop: the burst
    path's routed round (same ``_make_responders`` contract,
    ``cap_nq=None`` so capacities match the per-round burst engine —
    the replay identity) inside ONE psum-synchronised
    ``lax.while_loop`` with on-device early exit.  Carries a
    provisioned-solicitation-row counter (pending rows × α, the
    routed exchange's per-round row budget) for the trace's
    exchange accounting."""
    def body_fn(ids, tables_local, alive, st, rnd0):
        _, respond = _make_responders(
            cfg, n_shards, capacity_factor, False, ids, tables_local,
            alive)

        def cond(carry):
            st, it, _xr = carry
            pending = jax.lax.psum(jnp.sum(~st.done), AXIS)
            return (pending > 0) & (it < jnp.int32(rounds))

        def body(carry):
            st, it, xr = carry
            n_pend = jax.lax.psum(
                jnp.sum((~st.done).astype(jnp.int32)), AXIS)
            st = step_impl(ids, alive, respond, cfg, st,
                           rnd=rnd0 + it)
            return st, it + 1, xr + n_pend * jnp.int32(cfg.alpha)

        st, it, xr = jax.lax.while_loop(
            cond, body, (st, jnp.int32(0), jnp.int32(0)))
        return st, it, xr
    return body_fn


@partial(jax.jit, static_argnames=("cfg", "mesh", "capacity_factor",
                                   "rounds", "expire"),
         donate_argnums=(2, 3, 4))
def _sharded_resident_step(swarm, cfg, st, rings, cache, keys, reqs,
                           cls, key, n_new, rnd0, mesh,
                           capacity_factor, *, rounds, expire=True):
    """The mesh resident macro step (ISSUE 20): enqueue → pop →
    replicated-cache probe → MASKED routed init → scatter → one
    psum-synchronised routed round loop → shared harvest tail, all
    one program.

    The probe runs BEFORE the routed init and hit rows are handed to
    the init as ``skip`` — a mesh cache hit never rides the
    ``all_to_all`` (``xchg_init_rows`` counts only admitted rows, the
    provable counter).  Rings and cache are replicated like the
    burst engine's cache; the state is sharded exactly like the burst
    serve state, and every round is the burst path's routed round at
    the same round index, so the resident sharded replay is
    bit-identical to ``sharded_lookup(compact=False)``."""
    from ..models import serve as sv
    n_shards = mesh.shape[AXIS]
    c = st.done.shape[0]
    a = keys.shape[0]
    rings = sv._ring_enqueue(rings, keys, reqs, cls, n_new)
    rings, pkeys, preq, pcls, cand, valid = sv._ring_pop(st, rings, a)
    if cache is not None:
        hit_raw, h_found, h_hops = sv._probe_impl(cache, pkeys)
        hit = hit_raw & valid
    else:
        hit = jnp.zeros((a,), bool)
        h_found = jnp.full((a, cfg.quorum), -1, jnp.int32)
        h_hops = jnp.zeros((a,), jnp.int32)
    take = valid & ~hit
    new = _sharded_lookup_init_masked(swarm, cfg, pkeys, key, ~take,
                                      mesh, capacity_factor)
    eff = jnp.where(take, cand, jnp.int32(c))
    st = sv._scatter_rows_into(st, new, eff, rnd0)
    rings = rings._replace(
        slot_req=rings.slot_req.at[eff].set(preq, mode="drop"),
        slot_cls=rings.slot_cls.at[eff].set(pcls, mode="drop"))
    fn = shard_map(
        _resident_rounds_body(cfg, n_shards, capacity_factor, rounds),
        mesh=mesh,
        in_specs=(P(), P(AXIS, None), P(), _st_specs(True), P()),
        out_specs=(_st_specs(True), P(), P()), check_vma=False)
    st, rounds_run, xchg_round = fn(swarm.ids, swarm.tables,
                                    swarm.alive, st, rnd0)
    rnd_end = rnd0 + jnp.int32(rounds)
    st, rings, cache, comp, fin = sv._resident_tail(
        swarm.ids, cfg, st, rings, cache, rnd_end, expire)
    out = sv.ResidentOut(
        adm=jnp.sum(take.astype(jnp.int32)),
        hits=jnp.sum(hit.astype(jnp.int32)),
        queued=rings.tail - rings.head,
        head=rings.head, tail=rings.tail, shed=rings.shed,
        rounds_run=rounds_run,
        hit=hit,
        hit_req=jnp.where(hit, preq, -1),
        hit_found=h_found, hit_hops=h_hops,
        comp=comp,
        comp_req=jnp.where(comp, rings.slot_req, -1),
        comp_cls=jnp.where(comp, rings.slot_cls, -1),
        comp_hops=st.hops,
        comp_adm=st.admitted_round,
        comp_com=st.completed_round,
        comp_found=fin,
        rung_counts=jnp.zeros((1,), jnp.int32),
        xchg_init_rows=jnp.sum(take.astype(jnp.int32)),
        xchg_round_rows=xchg_round)
    st = st._replace(
        admitted_round=jnp.where(comp, -1, st.admitted_round))
    rings = rings._replace(
        slot_req=jnp.where(comp, -1, rings.slot_req),
        slot_cls=jnp.where(comp, -1, rings.slot_cls))
    return st, rings, cache, out


def _table_bytes_per_device(cfg: SwarmConfig, n_shards: int) -> int:
    return table_bytes(cfg) // max(1, n_shards)


@partial(jax.jit, static_argnames=("cfg", "n_shards"))
def _shard_pending_and_wneed(st, cfg: SwarmConfig, n_shards: int):
    """Fused per-burst readback pair for the sharded ladder: per-shard
    pending counts (the row ladder's worst-shard width driver) and the
    mesh-global live-slot watermark — ONE device program, ONE
    device_get, like the local loop's ``_pending_and_wneed``."""
    per_shard = jnp.sum(~st.done.reshape(n_shards, -1), axis=1)
    return per_shard, _pending_and_wneed(st, cfg)[1]


# ---------------------------------------------------------------------------
# straggler harvesting on the routed burst path
# ---------------------------------------------------------------------------
#
# The while formulation spins every shard in the psum'd cond until the
# SLOWEST shard drains; the burst formulation below instead repacks
# each shard's pending rows to the front between bursts and dispatches
# tail rounds on power-of-two-truncated per-shard prefixes (the local
# engine's shape ladder, shard-local so no rows cross shards and the
# routed capacity ranks are preserved — see models.swarm's compaction
# block comment).  The width must cover the WORST shard's pending
# count; the optional rebalance below fixes that load imbalance with
# one lossless all_to_all repack: every row gets a global stable rank
# (pending first) and moves to shard ``rank % D``, position
# ``rank // D`` — each shard ends with ⌈total/D⌉-balanced pending
# prefixes, so the whole mesh shrinks together.  Rebalance changes
# which shard a row queries from, which under a FINITE capacity_factor
# changes drop patterns — results are seed-identical to the
# uncompacted engine only at capacity_factor=inf (asserted in tests);
# plain compaction is seed-identical always.

def _sharded_compact_slice(st, order, mesh, w):
    track = st.admitted_round is not None

    def body(st, order):
        perm = _stable_done_perm(st.done)
        full = _permute_state(st, perm)
        return full, order[perm], LookupState(
            *[x if x is None else x[:w] for x in full])

    fn = shard_map(body, mesh=mesh,
                   in_specs=(_st_specs(track), P(AXIS)),
                   out_specs=(_st_specs(track), P(AXIS),
                              _st_specs(track)),
                   check_vma=False)
    return fn(st, order)


def _sharded_compact_resize(full, order, sub, mesh, w):
    track = full.admitted_round is not None

    def body(full, order, sub):
        wo = sub.done.shape[0]
        full = LookupState(*[f if f is None else f.at[:wo].set(s)
                             for f, s in zip(full, sub)])
        perm = _stable_done_perm(full.done)
        full = _permute_state(full, perm)
        return full, order[perm], LookupState(
            *[x if x is None else x[:w] for x in full])

    fn = shard_map(body, mesh=mesh,
                   in_specs=(_st_specs(track), P(AXIS),
                             _st_specs(track)),
                   out_specs=(_st_specs(track), P(AXIS),
                              _st_specs(track)),
                   check_vma=False)
    return fn(full, order, sub)


def _sharded_writeback(full, sub, mesh):
    track = full.admitted_round is not None

    def body(full, sub):
        wo = sub.done.shape[0]
        return LookupState(*[f if f is None else f.at[:wo].set(s)
                             for f, s in zip(full, sub)])

    fn = shard_map(body, mesh=mesh,
                   in_specs=(_st_specs(track), _st_specs(track)),
                   out_specs=_st_specs(track), check_vma=False)
    return fn(full, sub)


def _pack_rows(st: LookupState, order: jax.Array,
               pos: jax.Array) -> jax.Array:
    """Serialize state rows for the rebalance shuffle: ``[Ll, 10+3S]``
    uint32 — [valid flag | dest position | original row | hops | done |
    targets 5 | idx S | dist S | queried S]."""
    b32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
    c = lambda x: x[:, None]
    return jnp.concatenate(
        [c(jnp.ones(pos.shape, jnp.uint32)), c(b32(pos)), c(b32(order)),
         c(b32(st.hops)), c(st.done.astype(jnp.uint32)), st.targets,
         b32(st.idx), st.dist, st.queried.astype(jnp.uint32)], axis=1)


def _unpack_rows(rows: jax.Array, s: int):
    i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    st = LookupState(
        targets=rows[:, 5:10], idx=i32(rows[:, 10:10 + s]),
        dist=rows[:, 10 + s:10 + 2 * s],
        queried=rows[:, 10 + 2 * s:10 + 3 * s] != 0,
        done=rows[:, 4] != 0, hops=i32(rows[:, 3]))
    return st, i32(rows[:, 2])


def _rebalance_body(cfg, n_shards, w, st, order):
    """Per-shard rebalance kernel (inside shard_map): global stable
    rank → round-robin destination, routed LOSSLESSLY with the
    ``_bucketize``/``_fill_buckets`` machinery at capacity Ll (a
    source shard holds at most Ll rows, so no slot can overflow)."""
    ll = st.done.shape[0]
    me = jax.lax.axis_index(AXIS)
    pending = ~st.done
    pcount = jnp.sum(pending.astype(jnp.int32))
    counts = jax.lax.all_gather(pcount, AXIS)              # [D]
    start = jnp.sum(jnp.where(jnp.arange(n_shards) < me, counts, 0))
    total = jnp.sum(counts)
    # Global stable rank: pending rows 0..total-1 ordered by (shard,
    # local position), done rows after — a permutation of 0..L-1.
    lp = jnp.cumsum(pending.astype(jnp.int32)) - 1
    ld = jnp.cumsum((~pending).astype(jnp.int32)) - 1
    g = jnp.where(pending, start + lp,
                  total + me * ll - start + ld)            # [Ll]
    dest = (g % n_shards).astype(jnp.int32)
    pos = (g // n_shards).astype(jnp.int32)
    pay = _pack_rows(st, order, pos)
    src, _, _ = _bucketize(dest, jnp.ones((ll,), bool), n_shards, ll)
    buf = _fill_buckets(pay, src, n_shards, ll, 0)         # [D,Ll,W]
    a2a = partial(jax.lax.all_to_all, axis_name=AXIS, split_axis=0,
                  concat_axis=0, tiled=True)
    back = a2a(buf).reshape(n_shards * ll, -1)             # [D*Ll,W]
    valid = back[:, 0] == 1
    rpos = jnp.where(valid, jax.lax.bitcast_convert_type(
        back[:, 1], jnp.int32), ll)
    got = jnp.zeros((ll, pay.shape[1]), jnp.uint32
                    ).at[rpos].set(back, mode="drop")
    full, order = _unpack_rows(got, cfg.search_width)
    return full, order, LookupState(
        *[x if x is None else x[:w] for x in full])


def _sharded_rebalance_slice(st, order, cfg, mesh, w):
    n_shards = mesh.shape[AXIS]
    fn = shard_map(partial(_rebalance_body, cfg, n_shards, w),
                   mesh=mesh, in_specs=(_st_specs(), P(AXIS)),
                   out_specs=(_st_specs(), P(AXIS), _st_specs()),
                   check_vma=False)
    return fn(st, order)


def _sharded_rebalance_resize(full, order, sub, cfg, mesh, w):
    n_shards = mesh.shape[AXIS]

    def body(full, order, sub):
        wo = sub.done.shape[0]
        full = LookupState(*[f if f is None else f.at[:wo].set(s)
                             for f, s in zip(full, sub)])
        return _rebalance_body(cfg, n_shards, w, full, order)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(_st_specs(), P(AXIS), _st_specs()),
                   out_specs=(_st_specs(), P(AXIS), _st_specs()),
                   check_vma=False)
    return fn(full, order, sub)


# jit wrappers for the compaction plumbing: static width, donated
# carries (full/order are single-owner in the burst loop; sub's
# buffers fit neither output shape, so it is not donated).
_compact_slice_j = partial(jax.jit, static_argnames=("mesh", "w"),
                           donate_argnums=(0, 1))
_sharded_compact_slice = _compact_slice_j(_sharded_compact_slice)
_sharded_compact_resize = _compact_slice_j(_sharded_compact_resize)
_sharded_writeback = partial(
    jax.jit, static_argnames=("mesh",),
    donate_argnums=(0,))(_sharded_writeback)
_reb_j = partial(jax.jit, static_argnames=("cfg", "mesh", "w"),
                 donate_argnums=(0, 1))
_sharded_rebalance_slice = _reb_j(_sharded_rebalance_slice)
_sharded_rebalance_resize = _reb_j(_sharded_rebalance_resize)


def sharded_lookup(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                   key: jax.Array, mesh: Mesh,
                   capacity_factor: float = 2.0,
                   local_respond: bool = False,
                   compact: bool | None = None,
                   rebalance: bool = False,
                   stats: dict | None = None,
                   track_lifecycle: bool = False) -> LookupResult:
    """Full lookup batch with routing tables sharded over ``mesh``.

    ``swarm.tables`` is sharded on the node axis; ``ids`` and ``alive``
    replicated; ``targets`` sharded on the lookup axis.  N and L must
    divide the mesh size.  ``capacity_factor`` sizes the per-shard
    all_to_all buckets relative to the expected uniform load; queries
    past capacity retry next round.  ``local_respond`` is the 1-device
    decomposition aid (see :func:`_sharded_body`).

    Dispatches between two equivalent formulations on STATIC config:
    the collective-synchronised while-loop (faster at sizes whose
    per-device table fits twice in HBM; carries the table) and a
    host-driven burst loop like the local engine (table passed as a
    plain input each round, no duplication — how the 10M-node table
    runs on a 16 GB chip, where the while formulation is a measured
    OOM).  The burst formulation runs the straggler-harvesting ladder
    by default: per-shard done-compaction with power-of-two prefix
    dispatch, seed-identical to the uncompacted engine (capacity stays
    provisioned at the full batch width — ``cap_nq``).  ``compact``
    forces the choice: True = always the compacted burst formulation,
    False = never compact, None = dispatch on table size.
    ``rebalance`` additionally repacks pending rows ACROSS shards
    between bursts (lossless all_to_all; see the block comment — only
    bit-identical at ``capacity_factor=inf``), so the ladder tracks
    the mean pending load instead of the worst shard's; requesting it
    forces the compacted burst formulation (and is an error with
    ``compact=False``).  ``stats`` receives the dispatch-attribution
    fields like :func:`lookup` plus a ``formulation`` tag; the while
    formulation has no ladder, so it contributes only the tag.
    """
    if rebalance and compact is False:
        raise ValueError("rebalance=True requires the compacted burst "
                         "formulation (compact must not be False)")
    if track_lifecycle and rebalance:
        # The rebalance shuffle serializes a fixed row layout
        # (_pack_rows) that does not carry the lifecycle columns.
        raise ValueError("track_lifecycle is not supported with "
                         "rebalance=True")
    n_shards = mesh.shape[AXIS]
    fits_while = (2 * _table_bytes_per_device(cfg, n_shards)
                  + LOOKUP_HEADROOM_BYTES <= device_hbm_bytes())
    if compact is not True and not rebalance and not track_lifecycle \
            and fits_while:
        if stats is not None:
            stats["formulation"] = "while"
        return _sharded_lookup_while(swarm, cfg, targets, key, mesh,
                                     capacity_factor, local_respond)
    st = _sharded_lookup_init(swarm, cfg, targets, key, mesh,
                              capacity_factor, local_respond)
    if track_lifecycle:
        # Burst formulations only: the lifecycle rows ride the host-
        # driven carry (the while formulation's on-device loop has no
        # host round counter to stamp from).
        st = init_lifecycle(st)
    # Explicit cached upload for the per-round coordinate (see
    # utils/hostdevice; deliberately uncommitted so the scalar follows
    # the mesh placement) — same strict-transfer-guard hygiene as the
    # local burst loops.
    rnd_of = dev_i32 if track_lifecycle else (lambda r: None)
    if compact is False:
        if stats is not None:
            stats["formulation"] = "burst"
        st = run_burst_loop(
            lambda s, r: _sharded_lookup_step(swarm, cfg, s, mesh,
                                              capacity_factor,
                                              local_respond,
                                              rnd=rnd_of(r)),
            st, cfg)
        if track_lifecycle and stats is not None:
            stats["admitted_round"] = st.admitted_round
            stats["completed_round"] = st.completed_round
        found = _finalize(swarm.ids, st, cfg)
        return LookupResult(found=found, hops=st.hops, done=st.done)

    l = targets.shape[0]
    ll = l // n_shards
    cap_nq = ll * cfg.alpha       # capacity stays full-width provisioned
    order = jnp.arange(l, dtype=jnp.int32)
    full, sub, w = st, st, ll
    # Shortened first burst, like the local compacted loop: engage the
    # ladder at the done-curve knee (~2 rounds before the calibrated
    # exit) for one extra done-check readback.
    burst = max(2, burst_schedule(cfg) - 2)
    rounds = row_rounds = 0
    widths = []
    # Merge-width ladder (round 18): the same per-burst live-slot
    # watermark rung as the local loop, mesh-global (the rank planes
    # run per shard inside shard_map, so the rung must cover the WORST
    # shard's watermark — the max rides the same readback as pend).
    # Guarded in-jit, so a stale rung is bit-identical, just full
    # price.  XLA rank-merge path only; the while formulation and the
    # Pallas kernels keep their fixed-width programs.
    resp_w = cfg.alpha * 2 * cfg.bucket_k
    width_ladder = (resolve_merge_impl(cfg) == "xla"
                    and len(merge_ladder_widths(
                        resp_w, 2 * cfg.bucket_k)) > 1)
    merge_w = None
    merge_widths = []
    while rounds < cfg.max_steps:
        n = min(burst, cfg.max_steps - rounds)
        for _ in range(n):
            sub = _sharded_lookup_step(swarm, cfg, sub, mesh,
                                       capacity_factor, local_respond,
                                       cap_nq, rnd=rnd_of(rounds),
                                       merge_w=merge_w)
            rounds += 1
            row_rounds += w * n_shards
        if w not in widths:
            widths.append(w)
        if merge_w not in merge_widths:
            merge_widths.append(merge_w)
        # graftlint: disable=sync-in-loop (per-BURST done-check readback, amortized over >=2 device rounds — the ladder's contract; _sharded_resident_step is the zero-poll alternative, its psum'd early exit living in the shard_map while_loop cond)
        pend, wneed = jax.device_get(
            _shard_pending_and_wneed(sub, cfg, n_shards))
        total = int(pend.sum())
        if total == 0:
            break
        burst = 2
        if width_ladder:
            merge_w = pick_merge_width(int(wneed), resp_w,
                                       2 * cfg.bucket_k)
        if rebalance:
            w_new = _ladder_width(-(-total // n_shards), ll)
            if w_new < w:
                if w == ll:
                    full, order, sub = _sharded_rebalance_slice(
                        sub, order, cfg, mesh, w_new)
                else:
                    full, order, sub = _sharded_rebalance_resize(
                        full, order, sub, cfg, mesh, w_new)
                w = w_new
        else:
            w_new = _ladder_width(int(pend.max()), ll)
            if w_new < w:
                if w == ll:
                    full, order, sub = _sharded_compact_slice(
                        sub, order, mesh, w_new)
                else:
                    full, order, sub = _sharded_compact_resize(
                        full, order, sub, mesh, w_new)
                w = w_new
    full = _sharded_writeback(full, sub, mesh) if w < ll else sub
    if track_lifecycle and stats is not None:
        stats["admitted_round"] = _scatter_rows(full.admitted_round,
                                                order)
        stats["completed_round"] = _scatter_rows(full.completed_round,
                                                 order)
    if stats is not None:
        stats["formulation"] = ("burst-rebalanced" if rebalance
                                else "burst-compacted")
        stats["rounds_dispatched"] = rounds
        stats["dispatched_row_rounds"] = row_rounds
        stats["mean_active_frac"] = (
            round(row_rounds / (rounds * l), 4) if rounds else 0.0)
        stats["widths"] = widths
        if width_ladder:
            stats["merge_widths"] = [resp_w if mw is None else mw
                                     for mw in merge_widths]
    found = _scatter_rows(_finalize(swarm.ids, full, cfg), order)
    return LookupResult(found=found,
                        hops=_scatter_rows(full.hops, order),
                        done=_scatter_rows(full.done, order))


# ---------------------------------------------------------------------------
# flight recorder on the routed multi-chip path
# ---------------------------------------------------------------------------

# Trace fields that are per-shard PARTIAL sums (each shard counts its
# own lookup sub-batch) reduce with psum; fields computed from already-
# replicated state reduce with pmax — the chaos strike counters are
# psum-merged every round, so per-round strike/conviction telemetry is
# identical on every shard, and psum would multiply it by the mesh
# size.  ``rounds`` is lock-step-identical too.
_TRACE_PMAX_FIELDS = ("strikes", "convictions", "rounds")


def _trace_allreduce(trace: LookupTrace) -> LookupTrace:
    """ONE reduction of the whole trace at loop exit (inside
    shard_map): the result is replicated, so the caller's out_spec is
    ``P()`` and the host sees a single global trace."""
    return LookupTrace(*[
        jax.lax.pmax(v, AXIS) if f in _TRACE_PMAX_FIELDS
        else jax.lax.psum(v, AXIS)
        for f, v in zip(LookupTrace._fields, trace)])


def _trace_specs():
    return LookupTrace(*[P() for _ in LookupTrace._fields])


def _traced_sharded_body(cfg: SwarmConfig, n_shards: int,
                         capacity_factor: float, ids, tables_local,
                         alive, targets, key):
    """:func:`_sharded_body` with the flight recorder riding the
    while-loop carry — counters accumulate per shard inside the loop
    and all-reduce ONCE at exit (zero extra host syncs, zero extra
    collectives on the per-round path)."""
    ll = targets.shape[0]
    me = jax.lax.axis_index(AXIS)
    key = jax.random.fold_in(key, me)
    origins = _sample_origins(key, alive, ll)
    respond_init, respond = _make_responders(
        cfg, n_shards, capacity_factor, False, ids, tables_local, alive)
    st = init_impl(ids, respond_init, cfg, targets, origins)
    trace = empty_lookup_trace(cfg)

    def cond(carry):
        st, _, it = carry
        pending = jax.lax.psum(jnp.sum(~st.done), AXIS)
        return (pending > 0) & (it < cfg.max_steps)

    def body(carry):
        st, trace, it = carry
        st, trace = step_impl(ids, alive, respond, cfg, st,
                              trace=trace, rnd=it)
        return st, trace, it + 1

    st, trace, _ = jax.lax.while_loop(cond, body,
                                      (st, trace, jnp.int32(0)))
    return (_finalize(ids, st, cfg), st.hops, st.done,
            _trace_allreduce(trace))


@partial(jax.jit, static_argnames=("cfg", "mesh", "capacity_factor"))
def traced_sharded_lookup(swarm: Swarm, cfg: SwarmConfig,
                          targets: jax.Array, key: jax.Array,
                          mesh: Mesh, capacity_factor: float = 2.0
                          ) -> tuple[LookupResult, LookupTrace]:
    """Table-sharded lookups with the flight recorder on: returns
    ``(result, LookupTrace)`` with the trace psum/pmax-reduced across
    shards (replicated output).  Uses the while-loop formulation only —
    like :func:`chaos_sharded_lookup`, the recorder is a diagnostics
    tool for validation-scale runs, not the 10M-node burst dispatcher.
    """
    n_shards = mesh.shape[AXIS]
    fn = shard_map(
        partial(_traced_sharded_body, cfg, n_shards, capacity_factor),
        mesh=mesh,
        in_specs=(P(), P(AXIS, None), P(), P(AXIS, None), P()),
        out_specs=(P(AXIS, None), P(AXIS), P(AXIS), _trace_specs()),
        check_vma=False,
    )
    found, hops, done, trace = fn(swarm.ids, swarm.tables, swarm.alive,
                                  targets, key)
    return LookupResult(found=found, hops=hops, done=done), trace


# ---------------------------------------------------------------------------
# adversarial lookups on the routed multi-chip path
# ---------------------------------------------------------------------------

def _chaos_sharded_body(cfg: SwarmConfig, n_shards: int,
                        capacity_factor: float, faults: LookupFaults,
                        collect_trace: bool,
                        ids, tables_local, alive, byzantine, targets,
                        key):
    """Per-device chaos lookup loop: the shared adversarial round
    (``models.swarm.chaos_step_impl``) over the ROUTED respond.

    Fault injection and the strike/blacklist defense live entirely in
    the step wrapper, so the routed exchange needs no changes — poison
    replaces a Byzantine responder's returned window after the
    all_to_all brings it home, exactly where the local engine poisons
    its gather.  Strike events are merged mesh-wide with two ``[N]``
    psums per round (any-success-resets then accusations-add, an
    order-free formula identical to the local engine's), so a node
    convicted by lookups on one shard leaves shortlists on EVERY
    shard the same round — the multi-chip form of
    ``blacklist_node``'s global conviction.  Capacity drops of the
    bounded all_to_all do NOT strike (the origin shed those sends
    itself); only the fault model's in-transit losses do.
    """
    ll = targets.shape[0]
    me = jax.lax.axis_index(AXIS)
    key = jax.random.fold_in(key, me)
    origins = _sample_origins(key, alive & ~byzantine, ll)
    respond_init, respond = _make_responders(
        cfg, n_shards, capacity_factor, False, ids, tables_local,
        alive)
    st = init_impl(ids, respond_init, cfg, targets, origins)
    strikes = jnp.zeros((cfg.n_nodes,), jnp.int32)
    allreduce = lambda x: jax.lax.psum(x, AXIS)
    # Run-constant eclipse pool: hoisted out of the while-loop body so
    # the [N] argsort runs once per program, not once per round.
    byz_aux = (byz_colluder_pool(byzantine) if faults.eclipse
               else None)
    trace0 = empty_lookup_trace(cfg) if collect_trace else None

    def cond(carry):
        st = carry[0]
        it = carry[-1]
        pending = jax.lax.psum(jnp.sum(~st.done), AXIS)
        return (pending > 0) & (it < cfg.max_steps)

    if collect_trace:
        def body(carry):
            st, strikes, trace, it = carry
            st, strikes, trace = chaos_step_impl(
                ids, alive, byzantine, respond, cfg, faults, st,
                strikes, it, allreduce=allreduce, byz_aux=byz_aux,
                trace=trace)
            return st, strikes, trace, it + 1

        st, strikes, trace, _ = jax.lax.while_loop(
            cond, body, (st, strikes, trace0, jnp.int32(0)))
        trace = _trace_allreduce(trace)
    else:
        def body(carry):
            st, strikes, it = carry
            st, strikes = chaos_step_impl(
                ids, alive, byzantine, respond, cfg, faults, st,
                strikes, it, allreduce=allreduce, byz_aux=byz_aux)
            return st, strikes, it + 1

        st, strikes, _ = jax.lax.while_loop(
            cond, body, (st, strikes, jnp.int32(0)))
    # Last-round convictions would otherwise survive in done heads
    # (eviction runs at the start of the NEXT round, which the loop
    # exit skips) — censor reported results like the local engine.
    found = _censor_convicted(_finalize(ids, st, cfg), strikes, cfg,
                              faults)
    if collect_trace:
        return found, st.hops, st.done, strikes, trace
    return found, st.hops, st.done, strikes


@partial(jax.jit, static_argnames=("cfg", "mesh", "faults",
                                   "capacity_factor", "collect_trace"))
def chaos_sharded_lookup(swarm: Swarm, cfg: SwarmConfig,
                         targets: jax.Array, key: jax.Array, mesh: Mesh,
                         faults: LookupFaults = LookupFaults(),
                         capacity_factor: float = 2.0,
                         collect_trace: bool = False):
    """Table-sharded adversarial lookups: :func:`sharded_lookup` under
    the Byzantine fault model, with mesh-wide strike/blacklist state.

    Tables shard on the node axis, targets on the lookup axis;
    ``byzantine`` and the ``strikes`` counters are replicated like
    ``alive`` (each round's two [N] strike psums keep every shard's
    copy identical — see ``_chaos_sharded_body``).  Collective-
    synchronised while-loop formulation only: chaos scenarios run at
    sizes whose per-shard table fits twice in HBM (the 10M-node burst
    dispatcher is a throughput tool, not a fault harness).  Returns
    ``(LookupResult, strikes [N])``, plus a mesh-reduced
    :class:`~opendht_tpu.models.swarm.LookupTrace` when
    ``collect_trace`` is set.
    """
    n_shards = mesh.shape[AXIS]
    byz = (swarm.byzantine if swarm.byzantine is not None
           else jnp.zeros((cfg.n_nodes,), bool))
    out_specs = (P(AXIS, None), P(AXIS), P(AXIS), P())
    if collect_trace:
        out_specs = out_specs + (_trace_specs(),)
    fn = shard_map(
        partial(_chaos_sharded_body, cfg, n_shards, capacity_factor,
                faults, collect_trace),
        mesh=mesh,
        in_specs=(P(), P(AXIS, None), P(), P(), P(AXIS, None), P()),
        out_specs=out_specs,
        check_vma=False,
    )
    out = fn(swarm.ids, swarm.tables, swarm.alive, byz, targets, key)
    found, hops, done, strikes = out[:4]
    res = LookupResult(found=found, hops=hops, done=done)
    if collect_trace:
        return res, strikes, out[4]
    return res, strikes


# ---------------------------------------------------------------------------
# single-chip emulation of sharded-transport contention
# ---------------------------------------------------------------------------

@partial(jax.jit,
         static_argnames=("cfg", "n_shards", "capacity_factor"))
def contended_lookup(swarm: Swarm, cfg: SwarmConfig, targets: jax.Array,
                     key: jax.Array, n_shards: int,
                     capacity_factor: float
                     ) -> tuple[LookupResult, jax.Array, jax.Array]:
    """Lookup batch under the sharded transport's bounded-capacity rule,
    emulated with *logical* shards on one chip.

    Nodes partition into ``n_shards`` contiguous owner ranges; each
    round, solicitations route to their owner with per-shard capacity
    ``C = capacity_factor · Q/S``; over-capacity queries drop (the
    origin retries next round) exactly as in ``_route_respond`` — same
    position arithmetic (``_shard_positions``), no collectives.  This
    measures the *contention* consequences of the capacity rule
    (drop fraction, convergence-round inflation under Zipf-skewed
    targets) on real hardware without needing a real multi-chip mesh —
    the in-sim analogue of the reference shedding load via its rate
    limiter (/root/reference/include/opendht/network_engine.h:462).

    Returns ``(result, dropped_queries, attempted_queries)`` — the
    counters summed over all rounds.
    """
    n = cfg.n_nodes
    shard_n = n // n_shards
    q = targets.shape[0] * cfg.alpha
    if math.isfinite(capacity_factor):
        cap = min(q, max(cfg.alpha,
                         int(math.ceil(q / n_shards * capacity_factor))))
    else:
        cap = q
    base = _local_respond(swarm, cfg)

    def sent_mask(nid):
        flat = nid.reshape(-1)
        safe = jnp.clip(flat, 0, n - 1)
        # Same eligibility as _route_respond: dead-node solicitations
        # never ship, so they must not consume capacity slots or count
        # as attempts in the contention statistics.
        ok = (flat >= 0) & swarm.alive[safe]
        owner = jnp.clip(safe // shard_n, 0, n_shards - 1).astype(
            jnp.int32)
        pos = _shard_positions(owner, ok, n_shards)
        return (ok & (pos < cap)).reshape(nid.shape), ok

    def respond(tg, nid, nid_d0):
        resp, d0, ans = base(tg, nid, nid_d0)
        sent, _ = sent_mask(nid)
        width = resp.shape[1] // nid.shape[1]
        m = jnp.repeat(sent, width, axis=1)
        return (jnp.where(m, resp, -1),
                jnp.where(m, d0, jnp.uint32(0xFFFFFFFF)),
                ans & sent)

    origins = _sample_origins(key, swarm.alive, targets.shape[0])
    st = init_impl(swarm.ids, base, cfg, targets, origins)

    def cond(carry):
        st, _, _ = carry
        return ~jnp.all(st.done) & (jnp.max(st.hops) < cfg.max_steps)

    def body(carry):
        st, dropped, attempted = carry
        # Same selection the step will make — counters see exactly the
        # queries the capacity rule saw.
        sel, _, _ = _select_alpha(st, cfg)
        sel = jnp.where(st.done[:, None], -1, sel)
        sent, ok = sent_mask(sel)
        dropped += jnp.sum(ok.reshape(sel.shape) & ~sent)
        attempted += jnp.sum(ok)
        return (step_impl(swarm.ids, swarm.alive, respond, cfg, st),
                dropped, attempted)

    zero = jnp.int32(0)
    st, dropped, attempted = jax.lax.while_loop(
        cond, body, (st, zero, zero))
    res = LookupResult(found=_finalize(swarm.ids, st, cfg),
                       hops=st.hops, done=st.done)
    return res, dropped, attempted
