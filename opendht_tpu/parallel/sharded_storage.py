"""Mesh-sharded value storage: announce/get over a node-sharded store.

The single-chip storage engine (:mod:`opendht_tpu.models.storage`)
keeps every node's value slots in ``[N, S]`` tensors; this module runs
the same semantics with those tensors sharded over the 1-D ``"swarm"``
mesh axis, the storage half of the reference's inherently-multi-node
design (``Dht::onAnnounce`` / ``onGetValues``,
/root/reference/src/dht.cpp:3333-3399, 3202-3225).

Both ops follow the same two-phase shape as the sharded lookup:

1. the routed lock-step lookup finds each key's ``quorum`` closest
   nodes (:func:`opendht_tpu.parallel.sharded.sharded_lookup`, which
   itself dispatches between a while-loop and a host-burst
   formulation on table size);
2. storage requests — ``(owner-local row, key, value, seq)`` for
   announce, ``(owner-local row, key)`` probes for get — ship to the
   owning shard in the same fixed-capacity ``all_to_all`` buckets as
   routing queries, are applied/answered against the local store
   shard, and the per-request outcomes (accept bit / hit-value-seq)
   ship back to the origin shard for aggregation.

Requests past a shard's capacity are dropped for the round, costing a
replica (announce) or a probe (get) — the lock-step analogue of the
reference dropping packets under load and catching up via maintenance
(``Dht::dataPersistence``, /root/reference/src/dht.cpp:2887-2947).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.storage import (
    AnnounceReport,
    GetResult,
    StoreConfig,
    StoreTrace,
    SwarmStore,
    _key_match,
    _key_write,
    _payload_digest,
    _pick_payload,
    _pl_gather,
    _segment_rank,
    _store_insert,
    ack_listeners,
    cancel_listen,
    drop_exchanges,
    empty_store,
    expire,
    expire_listeners,
    refresh_listeners,
)
from ..models.swarm import Swarm, SwarmConfig
from ..ops.sha1 import sha1_words
from ..ops.xor_metric import N_LIMBS
from .mesh import AXIS, shard_map
from .sharded import _bucketize, _fill_buckets, sharded_lookup


def _u2i(x: jax.Array) -> jax.Array:
    return jax.lax.bitcast_convert_type(x.astype(jnp.uint32), jnp.int32)


def _i2u(x: jax.Array) -> jax.Array:
    return jax.lax.bitcast_convert_type(x, jnp.uint32)


def _cap_for(q: int, n_shards: int, capacity_factor: float) -> int:
    if math.isfinite(capacity_factor):
        return min(q, max(1, int(math.ceil(q / n_shards
                                           * capacity_factor))))
    return q


def _route_out(payload: jax.Array, owner: jax.Array, ok: jax.Array,
               n_shards: int, cap: int):
    """Ship ``payload [Q,W]`` rows to their owner shards in capacity-
    ``cap`` buckets (same sort-based scheme as routing queries — see
    ``opendht_tpu.parallel.sharded._bucketize``; scatters and 2-D
    fancy gathers run on the TPU's slow per-element paths).  Returns
    ``(rbuf [D,cap,W], pos, sent)``; dropped rows have ``sent``
    False."""
    src, pos, sent = _bucketize(owner, ok, n_shards, cap)
    qbuf = _fill_buckets(payload, src, n_shards, cap, -1)
    rbuf = jax.lax.all_to_all(qbuf, AXIS, split_axis=0, concat_axis=0,
                              tiled=True)
    return rbuf, pos, sent


def _route_back(resp: jax.Array, owner: jax.Array, pos: jax.Array,
                sent: jax.Array, cap: int) -> jax.Array:
    """Return per-request responses ``resp [D,cap,W]`` to their origin
    rows; unsent rows read -1."""
    n_shards = resp.shape[0]
    back = jax.lax.all_to_all(resp, AXIS, split_axis=0, concat_axis=0,
                              tiled=True)
    slot = owner * cap + jnp.clip(pos, 0, cap - 1)
    mine = back.reshape(n_shards * cap, -1)[slot]
    return jnp.where(sent[:, None], mine, -1)


def _probe_refresh(store_local: SwarmStore, scfg: StoreConfig,
                   r_node, r_key, r_seq, r_val, r_dig, now):
    """Owner-side announce probe + refresh (one exchange).

    The reference's two-phase announce probes ``SELECT id,seq`` at each
    synced replica and ships the full value only where it is missing or
    stale, sending a cheap ``refresh`` (TTL reset) otherwise
    (/root/reference/src/dht.cpp:1237-1339, refresh :1299-1307).  In
    the lock-step engine probe and refresh collapse into one routed
    exchange: the owner classifies each (key, seq, val, digest) probe
    against its store shard and refreshes matching replicas in place.

    ``r_dig`` is the announcer's payload digest
    (:func:`opendht_tpu.models.storage._payload_digest`): "fresh same"
    requires the stored BYTES to digest-match too, mirroring the edit
    policy's "data exactly the same" test — an equal-seq same-token
    different-bytes replica is a conflict (status 2), never counted as
    a completed replica for the announcer's bytes.

    Returns ``(status [M], store_local)`` with status 0 = missing or
    stale (send the full value), 1 = fresh same-value replica
    (refreshed — ``created`` reset to ``now``), 2 = replica fresher or
    equal-seq conflicting (skip: a full announce would be rejected by
    the edit policy anyway).
    """
    rows = store_local.used.shape[0]
    s = scfg.slots
    n_safe = jnp.clip(r_node, 0, rows - 1)
    valid = r_node >= 0
    km = store_local.used[n_safe] \
        & _key_match(store_local.keys, n_safe, s, r_key)  # [M,S]
    has = jnp.any(km, axis=-1)
    mslot = jnp.argmax(km, axis=-1).astype(jnp.int32)
    cur_seq = store_local.seqs[n_safe, mslot]
    cur_val = store_local.vals[n_safe, mslot]
    fresh_same = valid & has & (cur_seq == r_seq) & (cur_val == r_val)
    if scfg.payload_words:
        cur_dig = _payload_digest(_pl_gather(
            store_local.payload, n_safe * s + mslot, scfg.payload_words))
        fresh_same = fresh_same & (cur_dig == r_dig)
    need_full = valid & (~has | (cur_seq < r_seq))
    status = jnp.where(fresh_same, 1,
                       jnp.where(need_full, 0, 2))
    status = jnp.where(valid, status, -1)
    # Refresh: reset the matching slot's age (duplicate probes of the
    # same slot all write the same ``now`` — scatter-max is safe;
    # masked rows go out of bounds and drop).
    un = jnp.where(fresh_same, n_safe, rows)
    created = store_local.created.at[un, mslot].max(
        jnp.uint32(now), mode="drop")
    return status, store_local._replace(created=created)


def _insert_routed(cfg: SwarmConfig, scfg: StoreConfig, n_shards: int,
                   capacity_factor: float, alive,
                   store_local: SwarmStore, found, keys, vals, seqs,
                   sizes, ttls, now, payloads=None, probe=False,
                   full_capacity_factor=None):
    """Routed store-insert phase shared by announce and republish:
    ship each (replica-target, key, val, seq, size, ttl) request to the
    owning shard, apply it against the local store shard with the full
    edit-policy/budget semantics of ``_store_insert``, and route the
    accept bits back.

    ``probe=True`` enables the reference's two-phase announce (see
    :func:`_probe_refresh`): a 10-word probe/refresh exchange first
    (row + key5 + seq + val + payload digest, + the 1-word ack ride-
    back), then the full-value exchange ONLY for replicas that
    reported missing/stale, in buckets sized by
    ``full_capacity_factor`` (a maintenance sweep expects most
    replicas to refresh, so the full phase can be provisioned far
    below the probe phase; needy requests past its capacity retry next
    sweep).  Returns ``(store_local, replicas [ll], StoreTrace)`` —
    the trace leaves are psum-reduced here (one stacked [5] psum), so
    every shard holds the mesh-global sweep counters.  The exchange's
    wire cost is fully static — capacity buckets ship full-size
    regardless of fill — so the traffic accounting lives in
    :func:`storage_wire_words`, not on the device.
    """
    ll, quorum = found.shape
    shard_n = cfg.n_nodes // n_shards
    q = ll * quorum

    flat = found.reshape(-1)
    safe = jnp.clip(flat, 0, cfg.n_nodes - 1)
    ok = (flat >= 0) & alive[safe]
    owner = jnp.clip(safe // shard_n, 0, n_shards - 1).astype(jnp.int32)
    local_row = jnp.where(ok, safe - owner * shard_n, -1)

    w = scfg.payload_words
    rep = lambda a: jnp.repeat(a, quorum, axis=0)
    refreshed = jnp.zeros((q,), bool)
    if probe:
        dig = (_payload_digest(rep(payloads))
               if w and payloads is not None
               else jnp.zeros((q,), jnp.uint32))
        pcols = jnp.concatenate(
            [local_row[:, None], _u2i(rep(keys)),
             _u2i(rep(seqs))[:, None], _u2i(rep(vals))[:, None],
             _u2i(dig)[:, None]],
            axis=1)                                      # [Q, 9]
        cap1 = _cap_for(q, n_shards, capacity_factor)
        rbuf, pos1, sent1 = _route_out(pcols, owner, ok, n_shards, cap1)
        p_node = rbuf[..., 0].reshape(-1)
        p_key = _i2u(rbuf[..., 1:1 + N_LIMBS]).reshape(-1, N_LIMBS)
        p_seq = _i2u(rbuf[..., 1 + N_LIMBS]).reshape(-1)
        p_val = _i2u(rbuf[..., 2 + N_LIMBS]).reshape(-1)
        p_dig = _i2u(rbuf[..., 3 + N_LIMBS]).reshape(-1)
        status, store_local = _probe_refresh(store_local, scfg, p_node,
                                             p_key, p_seq, p_val,
                                             p_dig, now)
        back = _route_back(status.reshape(n_shards, cap1, 1), owner,
                           pos1, sent1, cap1)
        st = back[:, 0]
        refreshed = sent1 & (st == 1)
        ok = sent1 & (st == 0)      # only missing/stale go to phase 2
        if full_capacity_factor is None:
            full_capacity_factor = capacity_factor

    cols = [local_row[:, None], _u2i(rep(keys)),
            _u2i(rep(vals))[:, None], _u2i(rep(seqs))[:, None],
            _u2i(rep(sizes))[:, None], _u2i(rep(ttls))[:, None]]
    if w and payloads is not None:
        # Real value bytes ride the same routed request — the wire
        # form of the reference actually carrying the data.
        cols.append(_u2i(rep(payloads)))
    payload = jnp.concatenate(cols, axis=1)

    cap = _cap_for(q, n_shards,
                   full_capacity_factor if probe else capacity_factor)
    rbuf, pos, sent = _route_out(payload, owner, ok, n_shards, cap)

    r_node = rbuf[..., 0].reshape(-1)
    r_key = _i2u(rbuf[..., 1:1 + N_LIMBS]).reshape(-1, N_LIMBS)
    r_val = _i2u(rbuf[..., 1 + N_LIMBS]).reshape(-1)
    r_seq = _i2u(rbuf[..., 2 + N_LIMBS]).reshape(-1)
    r_size = _i2u(rbuf[..., 3 + N_LIMBS]).reshape(-1)
    r_ttl = _i2u(rbuf[..., 4 + N_LIMBS]).reshape(-1)
    m = r_node.shape[0]
    r_pl = (_i2u(rbuf[..., 5 + N_LIMBS:]).reshape(m, -1)
            if w and payloads is not None else None)
    # req_put = flat request index → _store_insert's replica vector
    # becomes a per-request accept bit we can route back.  Sizes ride
    # the wire VERBATIM (size 0 is a real recorded length — a
    # zero-length chunked part 0 — and must read back as 0, exactly as
    # on the local engine; invalid rows are dropped by their node
    # index, never by size).
    store_local, acc, trace = _store_insert(
        store_local, scfg, r_node, r_key, r_val, r_seq,
        jnp.arange(m, dtype=jnp.int32), now,
        r_size, r_ttl, r_pl)

    back = _route_back(acc.reshape(n_shards, cap, 1), owner, pos, sent,
                       cap)
    acc_mine = jnp.clip(back[:, 0], 0, 1).reshape(ll, quorum)
    # A refreshed replica counts as holding the value (the reference's
    # refresh ack completes the announce for that node, dht.cpp:1299).
    replicas = jnp.sum(acc_mine + refreshed.reshape(ll, quorum),
                       axis=1, dtype=jnp.int32)

    store_local = _merge_listener_state(store_local)
    # Mesh-global sweep telemetry: one stacked psum of the five scalar
    # counters — replicated, so the jit wrapper exposes it with P().
    tv = jax.lax.psum(jnp.stack(list(trace)), AXIS)
    trace = StoreTrace(*[tv[i] for i in range(len(trace))])
    return store_local, replicas, trace


def storage_wire_words(cfg: SwarmConfig, scfg: StoreConfig,
                       p_per_shard: int, n_shards: int,
                       capacity_factor: float, probe: bool = False,
                       full_capacity_factor: float | None = None
                       ) -> int:
    """Per-shard all_to_all payload words of one storage-insert
    exchange (:func:`_insert_routed`) — request buckets plus the
    1-word-per-slot response ride-back.

    Static by construction: the collectives ship their full capacity
    buckets regardless of how many rows are real, so this is exact
    accounting, not an estimate.  With ``probe`` the full-value phase
    shrinks to ``full_capacity_factor`` while a 10-word probe phase
    (9 request words incl. the payload digest, + 1 ack) is added — the
    reference's probe-then-put traffic shape
    (/root/reference/src/dht.cpp:1237-1339), where re-announcing a
    value most replicas already hold costs probes, not payloads.
    """
    q = p_per_shard * cfg.quorum
    w_full = 10 + scfg.payload_words + 1   # row+key5+val+seq+size+ttl+W, +ack
    if not probe:
        return _cap_for(q, n_shards, capacity_factor) * n_shards * w_full
    fcf = (capacity_factor if full_capacity_factor is None
           else full_capacity_factor)
    return (_cap_for(q, n_shards, capacity_factor) * n_shards * (9 + 1)
            + _cap_for(q, n_shards, fcf) * n_shards * w_full)


def _merge_listener_state(store_local: SwarmStore) -> SwarmStore:
    """Merge the shards' listener tables (global, replicated leaves).

    Notified bits OR together; delivery slots merge freshest-seq-wins
    with a single-winner shard pick — among the shards holding the
    mesh-max ``nseqs`` (slots store delivered_seq+1, so a first
    delivery always beats every stale replica), the highest-ranked one
    contributes val AND bytes, so cross-shard blending is impossible
    (same no-blend rule as :func:`_pick_payload`)."""
    notified = jax.lax.pmax(
        store_local.notified.astype(jnp.int32), AXIS).astype(bool)
    gseq = jax.lax.pmax(store_local.nseqs, AXIS)
    me = jax.lax.axis_index(AXIS).astype(jnp.int32)
    is_win = store_local.nseqs == gseq
    win_r = jax.lax.pmax(jnp.where(is_win, me, -1), AXIS)
    mine = is_win & (me == win_r)
    nvals = jax.lax.pmax(
        jnp.where(mine, store_local.nvals, 0), AXIS)
    npayload = jax.lax.pmax(
        jnp.where(mine[:, None], store_local.npayload, 0), AXIS)
    nsizes = jax.lax.pmax(
        jnp.where(mine, store_local.nsizes, 0), AXIS)
    return store_local._replace(notified=notified, nseqs=gseq,
                                nvals=nvals, npayload=npayload,
                                nsizes=nsizes)


def _probe_phase_body(cfg: SwarmConfig, scfg: StoreConfig,
                      n_shards: int, capacity_factor: float, alive,
                      store_local: SwarmStore, found, keys):
    """Per-shard get probes against the replicas a lookup ``found``
    (the storage half of ``Dht::onGetValues``, freshest-seq wins)."""
    ll, quorum = found.shape
    shard_n = cfg.n_nodes // n_shards
    q = ll * quorum

    flat = found.reshape(-1)
    safe = jnp.clip(flat, 0, cfg.n_nodes - 1)
    ok = (flat >= 0) & alive[safe]
    owner = jnp.clip(safe // shard_n, 0, n_shards - 1).astype(jnp.int32)
    local_row = jnp.where(ok, safe - owner * shard_n, -1)
    payload = jnp.concatenate(
        [local_row[:, None], _u2i(jnp.repeat(keys, quorum, axis=0))],
        axis=1)

    cap = _cap_for(q, n_shards, capacity_factor)
    rbuf, pos, sent = _route_out(payload, owner, ok, n_shards, cap)

    r_node = rbuf[..., 0].reshape(-1)
    r_key = _i2u(rbuf[..., 1:]).reshape(-1, N_LIMBS)
    shard_rows = store_local.used.shape[0]
    n_safe = jnp.clip(r_node, 0, shard_rows - 1)
    valid = r_node >= 0
    hit = store_local.used[n_safe] & valid[:, None] \
        & _key_match(store_local.keys, n_safe, scfg.slots, r_key)
    if scfg.verify:
        # Verified merge on the owner shard (see models.storage.
        # _get_probe): forged replicas are discarded BEFORE the
        # freshest-seq pick, so a corrupted copy never ships back.
        rows2 = n_safe[:, None] * scfg.slots \
            + jnp.arange(scfg.slots, dtype=jnp.int32)
        cand_pl = _pl_gather(store_local.payload, rows2,
                             scfg.payload_words)
        hit = hit & jnp.all(sha1_words(cand_pl) == r_key[:, None, :],
                            axis=-1)
    seq = jnp.where(hit, store_local.seqs[n_safe], 0)
    best = jnp.max(seq, axis=1)
    is_b = hit & (seq == best[:, None])
    val = jnp.max(jnp.where(is_b, store_local.vals[n_safe], 0), axis=1)
    anyhit = jnp.any(hit, axis=1)
    w = scfg.payload_words
    # Bytes of ONE winning replica ride back with the (hit, val, seq)
    # triple — flat per-column fetch, no small-minor gather on a big
    # payload operand (see models.storage._pl_gather).
    is_w = is_b & (store_local.vals[n_safe] == val[:, None])  # [M,S]
    sslots = scfg.slots
    wslot = jnp.argmax(is_w, axis=1).astype(jnp.int32)
    # The winner's recorded SIZE rides back with its bytes — a chunked
    # part-0 probe needs the true byte length the local engine's
    # ``_get_probe`` already returns.
    szv = jnp.where(anyhit, store_local.sizes[n_safe, wslot], 0)
    if w:
        pl = jnp.where(anyhit[:, None],
                       _pl_gather(store_local.payload,
                                  n_safe * sslots + wslot, w), 0)
    else:
        pl = jnp.zeros((is_w.shape[0], 0), jnp.uint32)

    resp = jnp.concatenate(
        [jnp.stack([anyhit.astype(jnp.int32), _u2i(val), _u2i(best),
                    _u2i(szv)],
                   axis=-1), _u2i(pl)],
        axis=-1).reshape(n_shards, cap, 4 + w)
    back = _route_back(resp, owner, pos, sent, cap)      # [Q,4+W]
    h = (back[:, 0] > 0).reshape(ll, quorum)
    v = _i2u(jnp.where(sent, back[:, 1], 0)).reshape(ll, quorum)
    s = _i2u(jnp.where(sent, back[:, 2], 0)).reshape(ll, quorum)
    q_szpl = _i2u(jnp.where(sent[:, None], back[:, 3:], 0)
                  ).reshape(ll, quorum, 1 + w)

    s = jnp.where(h, s, 0)
    best_seq = jnp.max(s, axis=1)
    win = h & (s == best_seq[:, None])
    best_val = jnp.max(jnp.where(win, v, 0), axis=1)
    # Single-replica pick across the quorum too (no word blending);
    # the size column rides the same pick so size and bytes can never
    # come from different replicas.
    out = _pick_payload(win & (v == best_val[:, None]), q_szpl,
                        jnp.any(h, axis=1))
    return (jnp.any(h, axis=1), best_val, best_seq, out[:, 1:],
            out[:, 0])


def _store_specs(mesh: Mesh) -> SwarmStore:
    """Per-leaf partition specs: node-axis leaves sharded, the global
    ``notified`` table replicated."""
    shd = P(AXIS)
    return SwarmStore(
        keys=P(AXIS), vals=P(AXIS, None), seqs=P(AXIS, None),
        created=P(AXIS, None), used=P(AXIS, None), cursor=shd,
        lkeys=P(AXIS), lids=P(AXIS), lexps=P(AXIS), lcursor=shd,
        notified=P(), sizes=P(AXIS, None), ttls=P(AXIS, None),
        payload=P(AXIS), nseqs=P(), nvals=P(),
        npayload=P(None, None), nsizes=P())


def shard_store(store: SwarmStore, mesh: Mesh) -> SwarmStore:
    """Lay an existing store out over the mesh (node axis)."""
    specs = _store_specs(mesh)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), store,
        specs)


@partial(jax.jit,
         static_argnames=("cfg", "scfg", "mesh", "capacity_factor",
                          "probe", "full_capacity_factor"),
         donate_argnums=(2,))
def _sharded_insert(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                    scfg: StoreConfig, found, keys, vals, seqs, sizes,
                    ttls, payloads, now, mesh: Mesh,
                    capacity_factor: float, probe: bool,
                    full_capacity_factor):
    """Jitted storage-insert phase: route the (replica, key, value)
    requests of an already-completed lookup to their owner shards."""
    n_shards = mesh.shape[AXIS]
    specs = _store_specs(mesh)

    def body(alive, store_local, found, keys, vals, seqs, sizes, ttls,
             payloads, now):
        return _insert_routed(cfg, scfg, n_shards, capacity_factor,
                              alive, store_local, found, keys, vals,
                              seqs, sizes, ttls, now, payloads,
                              probe=probe,
                              full_capacity_factor=full_capacity_factor)

    trace_specs = StoreTrace(*[P() for _ in StoreTrace._fields])
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), specs, P(AXIS, None), P(AXIS, None), P(AXIS),
                  P(AXIS), P(AXIS), P(AXIS), P(AXIS, None), P()),
        out_specs=(specs, P(AXIS), trace_specs), check_vma=False)
    return fn(swarm.alive, store, found, keys, vals, seqs, sizes, ttls,
              payloads, jnp.uint32(now))


def sharded_announce(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                     scfg: StoreConfig, keys: jax.Array,
                     vals: jax.Array, seqs: jax.Array, now,
                     key: jax.Array, mesh: Mesh,
                     capacity_factor: float = 4.0,
                     sizes: jax.Array | None = None,
                     ttls: jax.Array | None = None,
                     payloads: jax.Array | None = None,
                     probe: bool = False,
                     full_capacity_factor: float | None = None,
                     drop_frac: float = 0.0,
                     drop_key: jax.Array | None = None
                     ) -> Tuple[SwarmStore, AnnounceReport]:
    """Batched put over the sharded swarm + store.

    ``keys [P,5]`` / ``vals [P]`` / ``seqs [P]`` (and optional
    per-value ``sizes``/``ttls``) shard on the put axis; store shards
    on the node axis; P and N must divide the mesh size.  ``now`` is
    traced (a changing sim-time must not recompile).  ``probe``
    enables the reference's two-phase announce-with-probe (see
    :func:`_probe_refresh`; best for re-announces — a first put of
    fresh keys pays the probe for nothing).  ``drop_frac``/``drop_key``
    inject storage-RPC loss: a dropped replica target receives neither
    the probe nor the value for this round (the chaos-harness packet-
    loss knob, :func:`opendht_tpu.models.storage.drop_exchanges`).

    Two top-level phases — the routed lock-step lookup (which
    dispatches between its while-loop and burst formulations on table
    size, :func:`opendht_tpu.parallel.sharded.sharded_lookup`), then
    the routed insert exchange — so big-table swarms never carry the
    table through a device loop.
    """
    p = keys.shape[0]
    if sizes is None:
        sizes = jnp.ones((p,), jnp.uint32)
    if ttls is None:
        ttls = jnp.zeros((p,), jnp.uint32)
    if payloads is None:
        payloads = jnp.zeros((p, scfg.payload_words), jnp.uint32)
    res = sharded_lookup(swarm, cfg, keys, key, mesh, capacity_factor)
    found = drop_exchanges(res.found, drop_frac, drop_key)
    store, replicas, trace = _sharded_insert(
        swarm, cfg, store, scfg, found, keys, vals, seqs, sizes,
        ttls, payloads, now, mesh, capacity_factor, probe,
        full_capacity_factor)
    return store, AnnounceReport(replicas=replicas, hops=res.hops,
                                 done=res.done, trace=trace)


@partial(jax.jit,
         static_argnames=("cfg", "scfg", "mesh", "capacity_factor"))
def _sharded_probe_phase(swarm: Swarm, cfg: SwarmConfig,
                         store: SwarmStore, scfg: StoreConfig, found,
                         keys, mesh: Mesh, capacity_factor: float):
    n_shards = mesh.shape[AXIS]
    specs = _store_specs(mesh)
    fn = shard_map(
        partial(_probe_phase_body, cfg, scfg, n_shards,
                capacity_factor),
        mesh=mesh,
        in_specs=(P(), specs, P(AXIS, None), P(AXIS, None)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS, None), P(AXIS)),
        check_vma=False)
    return fn(swarm.alive, store, found, keys)


def sharded_get(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                scfg: StoreConfig, keys: jax.Array, key: jax.Array,
                mesh: Mesh, capacity_factor: float = 4.0) -> GetResult:
    """Batched get over the sharded swarm + store (freshest-seq wins).
    Same two-phase shape as :func:`sharded_announce`."""
    res = sharded_lookup(swarm, cfg, keys, key, mesh, capacity_factor)
    hit, val, seq, pl, _sz = _sharded_probe_phase(swarm, cfg, store,
                                                  scfg, res.found, keys,
                                                  mesh, capacity_factor)
    return GetResult(hit=hit, val=val, seq=seq, hops=res.hops,
                     done=res.done, payload=pl)


def sharded_empty_store(n_nodes: int, scfg: StoreConfig,
                        mesh: Mesh) -> SwarmStore:
    """An empty store laid out over the mesh."""
    return shard_store(empty_store(n_nodes, scfg), mesh)


# ---------------------------------------------------------------------------
# storage maintenance on the mesh (republish / expire / listen)
# ---------------------------------------------------------------------------

def sharded_republish(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                      scfg: StoreConfig, now, key: jax.Array,
                      mesh: Mesh, capacity_factor: float = 4.0,
                      probe: bool = False,
                      full_capacity_factor: float | None = None,
                      chunk: int = 262_144,
                      node_range: Tuple[int, int] | None = None,
                      drop_frac: float = 0.0,
                      drop_key: jax.Array | None = None
                      ) -> Tuple[SwarmStore, AnnounceReport]:
    """Mesh-wide storage maintenance: every alive node re-announces its
    stored values to the keys' current quorum-closest — the sharded
    ``Dht::dataPersistence``/``maintainStorage``
    (/root/reference/src/dht.cpp:2887-2947), restoring replication
    after churn without leaving the mesh.  The maintenance batch is
    every node's every slot (``N·slots`` lookups), processed in
    mesh-divisible ``chunk``-sized pieces so even the 10M-node store
    sweeps within HBM; over-capacity requests drop and are healed by
    the next sweep, like the reference's rate-limited maintenance
    catching up over successive 10-min periods.

    ``probe=True`` runs the two-phase announce-with-probe — pair it
    with a ``full_capacity_factor`` well below ``capacity_factor``
    (e.g. expected churn-lost fraction × capacity_factor): that is
    where the wire saving lands, since capacity buckets ship full-size
    regardless of fill.  With the default (full) provisioning a probe
    sweep COSTS 10 extra words per slot; maintenance is exactly the
    workload where a shrunk full phase is safe, because most replicas
    answer the probe with a refresh (``bench.py --mode repub``
    measures the trade).

    Chaos knobs: ``node_range=(lo, hi)`` restricts the sweep to that
    republisher range (both multiples of the mesh size), letting a
    harness kill nodes MID-maintenance — sweep the first half, churn,
    sweep the rest; ``drop_frac``/``drop_key`` lose a fraction of the
    announce/probe exchanges (:func:`opendht_tpu.models.storage.
    drop_exchanges`).
    """
    n_shards = mesh.shape[AXIS]
    s = scfg.slots
    lo0, hi0 = node_range if node_range is not None else (0, cfg.n_nodes)
    n = hi0 - lo0
    assert 0 <= lo0 < hi0 <= cfg.n_nodes \
        and lo0 % n_shards == 0 and n % n_shards == 0, (
            lo0, hi0, n_shards)
    # Chunk by NODE RANGE, boundaries aligned to whole nodes and the
    # mesh: each chunk slices the live store leaves directly (no
    # full-store snapshot copies held across the sweep — at 10M nodes
    # a keys+payload snapshot alone is GBs next to a ~10 GB table).
    # Later chunks see earlier chunks' inserts, like the reference's
    # maintenance iterating live storage.
    cn = min(n, max(n_shards, (chunk // s) // n_shards * n_shards))
    while n % cn:
        cn -= n_shards
    reps, hops, done = [], [], []
    trace = StoreTrace.zeros()
    for i, nlo in enumerate(range(lo0, hi0, cn)):
        nsl = slice(nlo, nlo + cn)
        keys = store.keys[nlo * s * N_LIMBS:
                          (nlo + cn) * s * N_LIMBS].reshape(cn * s,
                                                            N_LIMBS)
        # Dead/empty source slots announce to no one (the republisher
        # is the node OWNING the slot, so its aliveness gates the row).
        okf = (swarm.alive[nsl, None] & store.used[nsl]).reshape(-1)
        res = sharded_lookup(swarm, cfg, keys,
                             jax.random.fold_in(key, i), mesh,
                             capacity_factor)
        found = jnp.where(okf[:, None], res.found, -1)
        found = drop_exchanges(
            found, drop_frac,
            None if drop_key is None else jax.random.fold_in(drop_key, i))
        store, replicas, tr = _sharded_insert(
            swarm, cfg, store, scfg, found, keys,
            store.vals[nsl].reshape(-1), store.seqs[nsl].reshape(-1),
            store.sizes[nsl].reshape(-1), store.ttls[nsl].reshape(-1),
            store.payload[nlo * s * scfg.payload_words:
                          (nlo + cn) * s * scfg.payload_words
                          ].reshape(cn * s, scfg.payload_words),
            now, mesh,
            capacity_factor, probe, full_capacity_factor)
        trace = trace + tr
        reps.append(replicas), hops.append(res.hops), done.append(res.done)
    return store, AnnounceReport(replicas=jnp.concatenate(reps),
                                 hops=jnp.concatenate(hops),
                                 done=jnp.concatenate(done),
                                 trace=trace)


def sharded_expire(store: SwarmStore, scfg: StoreConfig,
                   now) -> SwarmStore:
    """TTL sweep over the sharded store (``Storage::expire``,
    /root/reference/src/dht.cpp:2361-2381).

    Elementwise on every ``[N,S]`` leaf — XLA runs it shard-local with
    zero communication under whatever ``NamedSharding`` the store
    carries, so the single-chip ``expire`` IS the sharded one."""
    return expire(store, scfg, now)


def _listen_body(cfg: SwarmConfig, scfg: StoreConfig, n_shards: int,
                 capacity_factor: float, alive,
                 store_local: SwarmStore, found, keys, reg_ids, now):
    """Per-shard listen phase: routed listener-table inserts (ring
    slots, ≤ listen_slots per node per batch) against the replicas a
    lookup ``found`` — the sharded ``Dht::storageAddListener``
    (/root/reference/src/dht.cpp:2299-2322).  Rows expire at
    ``now + scfg.listen_ttl`` (0 = never) unless refreshed."""
    from ..models.storage import INT32_MAX

    ll, quorum = found.shape
    shard_n = cfg.n_nodes // n_shards
    q = ll * quorum
    ls = scfg.listen_slots

    flat = found.reshape(-1)
    safe = jnp.clip(flat, 0, cfg.n_nodes - 1)
    rid = jnp.repeat(reg_ids, quorum)
    ok = (flat >= 0) & alive[safe] \
        & (rid >= 0) & (rid < scfg.max_listeners)
    owner = jnp.clip(safe // shard_n, 0, n_shards - 1).astype(jnp.int32)
    local_row = jnp.where(ok, safe - owner * shard_n, -1)
    payload = jnp.concatenate(
        [local_row[:, None], _u2i(jnp.repeat(keys, quorum, axis=0)),
         rid[:, None]], axis=1)

    cap = _cap_for(q, n_shards, capacity_factor)
    rbuf, pos, sent = _route_out(payload, owner, ok, n_shards, cap)

    r_node = rbuf[..., 0].reshape(-1)
    r_key = _i2u(rbuf[..., 1:1 + N_LIMBS]).reshape(-1, N_LIMBS)
    r_id = rbuf[..., 1 + N_LIMBS].reshape(-1)
    valid = r_node >= 0

    node_sk = jnp.where(valid, r_node, INT32_MAX)
    out = jax.lax.sort(
        (node_sk,) + tuple(r_key[:, i] for i in range(N_LIMBS))
        + (r_id, r_node),
        dimension=0, num_keys=1, is_stable=True)
    s_node_sk = out[0]
    s_key = jnp.stack(out[1:1 + N_LIMBS], axis=-1)
    s_id, s_node = out[1 + N_LIMBS], out[2 + N_LIMBS]
    live = s_node >= 0
    rank = _segment_rank(s_node_sk, live)
    accept = live & (rank < ls)
    rows = store_local.used.shape[0]
    n_safe = jnp.clip(s_node, 0, rows - 1)
    slot = ((store_local.lcursor[n_safe] + rank.astype(jnp.uint32))
            % jnp.uint32(ls)).astype(jnp.int32)
    nn = jnp.where(accept, s_node, rows)
    lkeys = _key_write(store_local.lkeys, nn * ls + slot, s_key)
    lids = store_local.lids.at[nn * ls + slot].set(s_id, mode="drop")
    exp = (jnp.uint32(now) + jnp.uint32(scfg.listen_ttl)
           if scfg.listen_ttl else jnp.uint32(0))
    lexps = store_local.lexps.at[nn * ls + slot].set(
        jnp.broadcast_to(exp, s_id.shape), mode="drop")
    n_new = jnp.zeros_like(store_local.lcursor).at[
        jnp.where(accept, s_node, 0)].add(accept.astype(jnp.uint32))
    store_local = store_local._replace(
        lkeys=lkeys, lids=lids, lexps=lexps,
        lcursor=store_local.lcursor + n_new)
    return store_local


@partial(jax.jit,
         static_argnames=("cfg", "scfg", "mesh", "capacity_factor"))
def _sharded_listen_phase(swarm, cfg, store, scfg, found, keys,
                          reg_ids, now, mesh, capacity_factor):
    n_shards = mesh.shape[AXIS]
    specs = _store_specs(mesh)
    fn = shard_map(
        partial(_listen_body, cfg, scfg, n_shards, capacity_factor),
        mesh=mesh,
        in_specs=(P(), specs, P(AXIS, None), P(AXIS, None), P(AXIS),
                  P()),
        out_specs=specs, check_vma=False)
    return fn(swarm.alive, store, found, keys, reg_ids,
              jnp.uint32(now))


def sharded_listen_at(swarm: Swarm, cfg: SwarmConfig, store: SwarmStore,
                      scfg: StoreConfig, keys: jax.Array,
                      reg_ids: jax.Array, key: jax.Array, mesh: Mesh,
                      capacity_factor: float = 4.0, now=0
                      ) -> Tuple[SwarmStore, jax.Array]:
    """Batched listen over the mesh: register listener ``reg_ids [P]``
    for ``keys [P,5]`` at each key's quorum-closest nodes; subsequent
    ``sharded_announce``/``sharded_republish`` of a key push the
    changed value into its listeners' delivery slots (merged
    mesh-wide).  Same two-phase shape as :func:`sharded_announce`.
    With ``scfg.listen_ttl`` set, registrations expire at ``now +
    listen_ttl`` unless refreshed (:func:`sharded_refresh_listeners`)."""
    res = sharded_lookup(swarm, cfg, keys, key, mesh, capacity_factor)
    store = _sharded_listen_phase(swarm, cfg, store, scfg, res.found,
                                  keys, reg_ids, now, mesh,
                                  capacity_factor)
    return store, res.done


# The listener-lifecycle sweeps are elementwise over the (sharded)
# listener table with replicated id masks — XLA runs them shard-local
# under the store's NamedSharding with zero communication, so the
# single-chip ops ARE the sharded ones (same pattern as
# :func:`sharded_expire`).  Re-exported under sharded_* names so call
# sites read symmetrically with the other mesh ops.

def sharded_cancel_listen(store: SwarmStore, scfg: StoreConfig,
                          reg_ids: jax.Array) -> SwarmStore:
    """Mesh-wide ``Dht::cancelListen``: the canceled ids' table rows
    die on EVERY shard and their (replicated) delivery slots clear."""
    return cancel_listen(store, scfg, reg_ids)


def sharded_refresh_listeners(store: SwarmStore, scfg: StoreConfig,
                              active: jax.Array, now) -> SwarmStore:
    """Mesh-wide listener re-register sweep (the reference's ~30 s
    keepalive): rows of ``active`` ids get expiry ``now+listen_ttl``."""
    return refresh_listeners(store, scfg, active, now)


def sharded_expire_listeners(store: SwarmStore, scfg: StoreConfig,
                             now) -> SwarmStore:
    """Mesh-wide reclaim of lapsed listener registrations."""
    return expire_listeners(store, scfg, now)


def sharded_ack_listeners(store: SwarmStore,
                          reg_ids: jax.Array) -> SwarmStore:
    """Mesh-wide reader ack: consume delivery slots so the next
    accepted announce re-delivers (see
    :func:`opendht_tpu.models.storage.ack_listeners`)."""
    return ack_listeners(store, reg_ids)


# ---------------------------------------------------------------------------
# chunked values on the mesh (variable-size multi-part values)
# ---------------------------------------------------------------------------

from ..models.chunked_values import (  # noqa: E402
    ChunkedGetResult,
    _chunked_root_ok,
    ack_chunked,
    collect_chunked,
    cancel_chunked,
    mask_chunk_payloads,
    part_key,
)


def sharded_announce_chunked(swarm: Swarm, cfg: SwarmConfig,
                             store: SwarmStore, scfg: StoreConfig,
                             keys: jax.Array, vals: jax.Array,
                             seqs: jax.Array, now, key: jax.Array,
                             mesh: Mesh, payloads: jax.Array,
                             lengths: jax.Array,
                             capacity_factor: float = 4.0,
                             drop_frac: float = 0.0,
                             drop_key: jax.Array | None = None,
                             part_drop_mask: jax.Array | None = None,
                             part_range: Tuple[int, int] | None = None
                             ) -> Tuple[SwarmStore, AnnounceReport]:
    """Batched put of variable-size values over the mesh — the routed
    twin of :func:`opendht_tpu.models.chunked_values.announce_chunked`.

    ``payloads [P, parts, W]`` / ``lengths [P]``; ONE routed lookup per
    base key (all parts share the closest-node set), then one routed
    insert exchange per active part at its part key.  Parts insert
    through the UNVERIFIED programs (part keys are key-derived, not
    content-derived — see the chunked_values module docstring);
    integrity lives at the read merge.  The report's ``trace`` is the
    SUM of the per-part mesh-global traces, so whole-sweep conservation
    (``requests == accepts + rejects``) holds across parts exactly.

    Chaos knobs, composing the republish harness's shapes:

    * ``drop_frac``/``drop_key`` — storage-RPC loss; the key is
      ``fold_in``-split per part, so loss is independent across parts
      (a torn write: SOME parts of a value land);
    * ``part_drop_mask [P, parts]`` — deterministic per-part drops
      (True = this value's part j is not announced at all);
    * ``part_range=(lo, hi)`` — announce only parts ``lo ≤ j < hi``: a
      mid-announce kill between parts (the writer died after part
      ``hi-1`` left the NIC).  ``replicas`` reports 0 when part 0 is
      outside the range.
    """
    p, parts, w = payloads.shape
    assert w == scfg.payload_words, (w, scfg.payload_words)
    payloads, lengths = mask_chunk_payloads(payloads, lengths)
    words = -(-lengths.astype(jnp.int32) // 4)               # [P]
    part_scfg = scfg._replace(verify=False)
    res = sharded_lookup(swarm, cfg, keys, key, mesh, capacity_factor)
    lo, hi = part_range if part_range is not None else (0, parts)
    assert 0 <= lo < hi <= parts, (lo, hi, parts)
    ttls = jnp.zeros((p,), jnp.uint32)
    rep0 = jnp.zeros((p,), jnp.int32)
    trace = StoreTrace.zeros()
    for j in range(lo, hi):
        active = (words > j * w) | (j == 0)
        if part_drop_mask is not None:
            active = active & ~part_drop_mask[:, j]
        found_j = jnp.where(active[:, None], res.found, -1)
        found_j = drop_exchanges(
            found_j, drop_frac,
            None if drop_key is None else jax.random.fold_in(drop_key, j))
        sizes_j = (lengths.astype(jnp.uint32) if j == 0
                   else jnp.ones((p,), jnp.uint32))
        store, rep, tr = _sharded_insert(
            swarm, cfg, store, part_scfg, found_j, part_key(keys, j),
            vals, seqs, sizes_j, ttls, payloads[:, j], now, mesh,
            capacity_factor, False, None)
        trace = trace + tr
        if j == 0:
            rep0 = rep
    return store, AnnounceReport(replicas=rep0, hops=res.hops,
                                 done=res.done, trace=trace)


def sharded_get_chunked(swarm: Swarm, cfg: SwarmConfig,
                        store: SwarmStore, scfg: StoreConfig,
                        keys: jax.Array, key: jax.Array, mesh: Mesh,
                        parts: int, capacity_factor: float = 4.0
                        ) -> ChunkedGetResult:
    """Batched get of variable-size values over the mesh — the routed
    twin of :func:`opendht_tpu.models.chunked_values.get_chunked`,
    preserving the module contract mesh-wide: ``hit`` iff part 0 is
    found and every needed part carries part-0's ``(val, seq)``; a
    torn, partially-dropped or over-budget value reads as MISSING,
    never truncated or garbled.  With ``scfg.verify`` the reassembled
    bytes must also hash back to the base key
    (:func:`~opendht_tpu.models.chunked_values._chunked_root_ok`, in-
    jit) — a forged or bit-flipped part downgrades the row to missing.
    """
    p = keys.shape[0]
    w = scfg.payload_words
    part_scfg = scfg._replace(verify=False)
    res = sharded_lookup(swarm, cfg, keys, key, mesh, capacity_factor)
    h0, val, seq, pl0, sz = _sharded_probe_phase(
        swarm, cfg, store, part_scfg, res.found, keys, mesh,
        capacity_factor)
    need_words = -(-sz.astype(jnp.int32) // 4)               # [P]
    n_parts = jnp.clip(-(-need_words // max(w, 1)), 1, parts)
    ok = h0 & (need_words <= parts * w)
    pls = [pl0]
    for j in range(1, parts):
        hj, vj, sj, plj, _szj = _sharded_probe_phase(
            swarm, cfg, store, part_scfg, res.found, part_key(keys, j),
            mesh, capacity_factor)
        needed = n_parts > j
        ok = ok & (~needed | (hj & (vj == val) & (sj == seq)))
        pls.append(jnp.where(needed[:, None], plj, 0))
    payload = jnp.concatenate(pls, axis=1)                   # [P,parts*W]
    idx = jnp.arange(parts * w, dtype=jnp.int32)[None, :]
    payload = jnp.where(idx < need_words[:, None], payload, 0)
    if scfg.verify:
        ok = ok & _chunked_root_ok(keys, payload.reshape(p, parts, w),
                                   sz.astype(jnp.uint32))
    payload = jnp.where(ok[:, None], payload, 0)
    return ChunkedGetResult(
        hit=ok, val=jnp.where(ok, val, 0), seq=jnp.where(ok, seq, 0),
        length=jnp.where(ok, sz, 0), payload=payload,
        hops=res.hops, done=res.done)


def sharded_listen_chunked(swarm: Swarm, cfg: SwarmConfig,
                           store: SwarmStore, scfg: StoreConfig,
                           keys: jax.Array, reg_ids: jax.Array,
                           key: jax.Array, mesh: Mesh, parts: int,
                           capacity_factor: float = 4.0, now=0
                           ) -> Tuple[SwarmStore, jax.Array]:
    """Register chunked listeners over the mesh: one routed lookup per
    base key, a routed listener-table insert per part key — future
    announces of ANY part deliver into the logical listener's per-part
    slots, and :func:`sharded_collect_chunked` reassembles the value
    LIST under the get-merge guard.  Needs ``listen_slots ≥ parts``;
    all parts ride ONE insert batch so a node holds a registration
    whole or not at all (see the local twin's docstring)."""
    res = sharded_lookup(swarm, cfg, keys, key, mesh, capacity_factor)
    rid = jnp.asarray(reg_ids, jnp.int32)
    found_b = jnp.tile(res.found, (parts, 1))
    keys_b = jnp.concatenate([part_key(keys, j) for j in range(parts)])
    rid_b = jnp.concatenate([jnp.where(rid >= 0, rid * parts + j, -1)
                             for j in range(parts)])
    store = _sharded_listen_phase(swarm, cfg, store, scfg, found_b,
                                  keys_b, rid_b, now, mesh,
                                  capacity_factor)
    return store, res.done


# Delivery-slot collect/ack/cancel are elementwise over the REPLICATED
# listener-delivery leaves — shard-local under the store's sharding,
# so the single-chip ops ARE the sharded ones (same pattern as
# sharded_ack_listeners).

def sharded_collect_chunked(store: SwarmStore, scfg: StoreConfig,
                            reg_ids: jax.Array, parts: int,
                            keys: jax.Array | None = None):
    """Mesh-wide chunked delivery collect (see
    :func:`opendht_tpu.models.chunked_values.collect_chunked`)."""
    return collect_chunked(store, scfg, reg_ids, parts, keys)


def sharded_ack_chunked(store: SwarmStore, reg_ids: jax.Array,
                        parts: int) -> SwarmStore:
    """Mesh-wide chunked listener ack — consume all part slots."""
    return ack_chunked(store, reg_ids, parts)


def sharded_cancel_chunked(store: SwarmStore, scfg: StoreConfig,
                           reg_ids: jax.Array, parts: int) -> SwarmStore:
    """Mesh-wide chunked listener cancel."""
    return cancel_chunked(store, scfg, reg_ids, parts)
