"""Device-mesh helpers for the swarm engine.

The reference scales by running more processes on more hosts
(python/tools/dht/network.py's netns clusters); the TPU design scales
by sharding the swarm's tensors over a ``jax.sharding.Mesh`` and
letting XLA insert ICI collectives.  One 1-D axis (``"swarm"``) is
enough for both parallel modes:

* **data-parallel lookups** — node state replicated, the lookup batch
  axis sharded (small swarms, many lookups);
* **table-sharded lookups** — routing tables (the HBM-dominant array:
  ``N·B·K·4`` bytes) sharded on the node axis, with queries routed to
  owner shards via ``all_to_all`` (see ``sharded.py``).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "swarm"


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma=False):
    """Version-compat ``shard_map``: newer jax exposes it as
    ``jax.shard_map`` (with ``check_vma``); older runtimes (e.g. the
    0.4.x line this container bakes in) only have
    ``jax.experimental.shard_map.shard_map`` (with ``check_rep``).
    One call-site API for both, so the sharded engine runs wherever
    the package imports."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(n_devices: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, ndim: int, axis: str = AXIS) -> NamedSharding:
    """Shard the leading axis; replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))
