"""Mesh sharding: data-parallel and table-sharded swarm lookups."""

from .mesh import AXIS, batch_sharded, make_mesh, replicated  # noqa: F401
from .sharded import (  # noqa: F401
    chaos_sharded_lookup,
    data_parallel_lookup,
    sharded_lookup,
)
