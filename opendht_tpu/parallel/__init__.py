"""Mesh sharding: data-parallel and table-sharded swarm lookups."""

from .mesh import AXIS, batch_sharded, make_mesh, replicated  # noqa: F401
from .sharded import data_parallel_lookup, sharded_lookup  # noqa: F401
