"""Swarm-engine benchmark: batched Kademlia lookups on real hardware.

Prints ONE JSON line:
  {"metric": "swarm_lookups_per_sec", "value": ..., "unit": "lookups/s",
   "vs_baseline": ...}

``vs_baseline`` divides by a **measured** number (BASELINE.md,
"Measured self-baseline"): the wall-clock rate at which the
reference's event-driven architecture — reproduced by this repo's host
path (core/dht.py over the virtual UDP transport, same α=4 / k=8 /
retry constants) — resolves random-key gets on this same machine:
139.7 lookups/s (32-node cluster, 500 gets; `python -m
opendht_tpu.harness.benchmark --performance -t gets`).  The C++
reference itself has no published numbers and its deps (gnutls,
nettle, msgpack-c) are not installable in this container.

Extra context fields (hop count, recall, swarm size) ride along in the
same JSON object.
"""

import argparse
import hashlib
import json
import math
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# Measured: BASELINE.md config 2 (event-driven host path, this machine).
REFERENCE_LOOKUPS_PER_SEC = 140.0


class LookupResultConcat:
    """Concatenated view over per-chunk LookupResults (host-side)."""

    def __init__(self, results):
        self.found = jnp.concatenate([r.found for r in results])
        self.hops = jnp.concatenate([r.hops for r in results])
        self.done = jnp.concatenate([r.done for r in results])


def even_chunk_size(total: int, target: int, multiple: int = 1) -> int:
    """Chunk size near ``target`` that divides ``total`` evenly (and is
    a multiple of ``multiple`` — mesh divisibility for sharded runs).
    A ragged last chunk would compile every program twice; prefer a few
    more even chunks.  Falls back to a ragged split only when no even
    divisor exists within 2× of the target."""
    n0 = max(1, -(-total // target))
    for n in range(n0, 2 * n0 + 1):
        if total % n == 0 and (total // n) % multiple == 0:
            return total // n
    # Ragged fallback: keep every chunk multiple-aligned, so the tail
    # (total - k·chunk) is too whenever total itself is — sharded
    # callers must still pass a mesh-divisible total.
    c = -(-total // n0)
    return -(-c // multiple) * multiple


def main():
    # Initialize the backend FIRST: config construction must never
    # touch the backend itself (dryrun invariant), and without a live
    # backend the HBM-derived cutoffs (and the --aug help text below)
    # would read the conservative 16 GB-class fallback instead of this
    # device's memory_stats().
    jax.devices()
    from opendht_tpu.models.swarm import _aug_table_budget

    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=None,
                    help="swarm size (default: 1M; churn mode: 100k)")
    ap.add_argument("--lookups", type=int, default=1_000_000)
    ap.add_argument("--puts", type=int, default=100_000,
                    help="announce/get batch for --mode putget")
    ap.add_argument("--aug", choices=("auto", "on", "off"),
                    default="auto",
                    help="augmented tables (auto: on while the "
                         "[N,B,3K] u16 table fits the budget derived "
                         "from this device's memory_stats() — "
                         f"~{_aug_table_budget() / 1e9:.1f} GB here, "
                         "lookup headroom already subtracted; includes "
                         "the 10M-node north star on a 16 GB chip)")
    ap.add_argument("--lookup-batch", type=int, default=0,
                    help="split lookups into device batches of this "
                         "size (0 = single batch); lets big-N swarms "
                         "use augmented tables within HBM")
    ap.add_argument("--repeat", type=int, default=3,
                    help="timed steady-state runs (R); the warm-up "
                         "run that triggers compilation is always "
                         "excluded, and the BENCH row reports "
                         "p50/p95 wall across the R runs next to the "
                         "best-of (wall_s), so compile time cannot "
                         "leak into any reported number")
    ap.add_argument("--compact", choices=("auto", "on", "off"),
                    default="auto",
                    help="straggler-harvesting lookup compaction "
                         "(auto = on; off = full-width dispatch every "
                         "round, the pre-ladder engine — for A/B "
                         "attribution)")
    ap.add_argument("--merge-impl",
                    choices=("auto", "xla", "xla-sort", "pallas",
                             "pallas-round"),
                    default="auto",
                    help="round-merge micro-architecture (auto = fused "
                         "Pallas kernel on TPU, XLA narrowed-plane "
                         "rank-merge + width ladder elsewhere; "
                         "xla-sort = the pre-round-9 two-pass sorted "
                         "merge — for A/B attribution; pallas = the "
                         "merge-only fused kernel; pallas-round = the "
                         "whole-round fused kernel (gather + decode + "
                         "merge VMEM-resident, local aug-table engine "
                         "only); either pallas variant off-TPU runs "
                         "the interpreter and is for tests only)")
    ap.add_argument("--recall-sample", type=int, default=512)
    ap.add_argument("--mode",
                    choices=("lookups", "putget", "churn", "crawl",
                             "sharded", "hotshard", "repub", "chaos",
                             "chaos-lookup", "repub-profile", "serve",
                             "monitor", "index", "soak", "auth",
                             "chunked"),
                    default="lookups")
    ap.add_argument("--kill-frac", type=float, default=None,
                    help="fraction of nodes killed (churn/chaos: 0.5; "
                         "chaos-lookup: 0.10)")
    ap.add_argument("--drop-frac", type=float, default=0.15,
                    help="chaos mode: fraction of announce/probe "
                         "exchanges lost per maintenance sweep; "
                         "chaos-lookup mode: fraction of lookup "
                         "solicitation replies lost in transit")
    ap.add_argument("--byzantine-frac", type=float, default=None,
                    help="chaos-lookup mode: fraction of nodes that "
                         "answer with poisoned closest-node windows "
                         "(default 0.05); monitor mode: mark this "
                         "fraction Byzantine and run sweeps through "
                         "the defended chaos engine (default 0 — a "
                         "convicted liar stops being seen and is "
                         "eventually presumed departed)")
    ap.add_argument("--poison", choices=("random", "eclipse"),
                    default="random",
                    help="chaos-lookup mode: Byzantine poison shape — "
                         "random node ids claimed near-zero, or "
                         "colluder-promotion eclipse")
    ap.add_argument("--zipf", type=float, default=None,
                    help="churn mode: draw gets Zipf(s)-skewed over "
                         "the put keyset (0 = uniform, one get/key; "
                         "default 0); hotshard mode: target skew "
                         "(default 1.2); serve mode: request-key "
                         "popularity (0 = uniform, default 1.1)")
    ap.add_argument("--shards", type=int, default=8,
                    help="hotshard mode: logical owner shards")
    ap.add_argument("--slots", type=int, default=0,
                    help="putget/churn: store slots per node (0 = "
                         "auto: 16, scaled down at big N so the "
                         "[N,slots] store fits HBM beside the routing "
                         "table)")
    ap.add_argument("--payload-words", type=int, default=0,
                    help="putget: attach real 4*W-byte value payloads "
                         "(verified on get); 0 = token-only store")
    ap.add_argument("--value-parts", type=int, default=0,
                    help="putget: store VARIABLE-size values spanning "
                         "up to this many W-word slots per value "
                         "(models.chunked_values; random per-value "
                         "lengths, bytes+length verified on get)")
    ap.add_argument("--rounds", type=lambda s: max(1, int(s)), default=1,
                    help="churn mode: kill/republish cycles, min 1 "
                         "(the mult_time persistence scenario)")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture an XLA profiler trace of one timed run")
    ap.add_argument("--trace-out", metavar="FILE", default=None,
                    help="flight recorder: dump the per-round device "
                         "trace (requests/replies/drops/churn/done per "
                         "round + hop-count histogram) of the last "
                         "timed run as JSON alongside the BENCH row "
                         "(lookups and chaos-lookup modes)")
    ap.add_argument("--ledger-out", metavar="FILE", default=None,
                    help="cost ledger: dump the per-kernel cost "
                         "attribution artifact (XLA cost_analysis "
                         "FLOPs/bytes, HBM watermarks, round sub-phase "
                         "A/B table; repub-profile mode: the sweep "
                         "phase table) as JSON — validated by "
                         "tools/check_trace.py, priced by "
                         "tools/roofline.py (lookups, sharded and "
                         "repub-profile modes)")
    ap.add_argument("--decompose", action="store_true",
                    help="sharded mode: measure the overhead ladder "
                         "(local bursts → shard_map/while_loop "
                         "structure → routing machinery → capacity "
                         "rule) on a 1-device mesh")
    ap.add_argument("--track-lifecycle", action="store_true",
                    help="lookups mode: run with the per-request "
                         "lifecycle plane ON (admitted/completed round "
                         "per row) — the A/B knob behind the <=5% "
                         "tracking-overhead budget")
    ap.add_argument("--arrival-rate", type=float, default=2000.0,
                    help="serve mode: open-loop Poisson arrival rate "
                         "(req/s)")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="serve mode: arrival-schedule horizon in "
                         "seconds (capped at 120 s so a serve leg can "
                         "never eat the tier-1 gate timeout)")
    def serve_slots_arg(s):
        """int slot count or the literal 'auto'."""
        return s if s == "auto" else int(s)

    ap.add_argument("--serve-slots", default=2048,
                    type=serve_slots_arg,
                    help="serve mode: resident lookup slots (finished "
                         "rows' slots admit NEW requests mid-flight); "
                         "'auto' sizes the slot plane from arrival "
                         "rate x measured round wall (Little's law at "
                         "0.5 target occupancy — the r07 0.15-"
                         "occupancy finding) and logs the choice in "
                         "the BENCH row")
    ap.add_argument("--sharded", action="store_true",
                    help="serve mode: drive the mesh serve engine "
                         "(ShardedServeEngine — routed per-round "
                         "exchanges over all available devices; "
                         "slots and admit cap must divide the mesh)")
    ap.add_argument("--serve-cache", type=int, default=0,
                    help="serve/soak modes: device hot-key result-"
                         "cache slots (0 = off; the cache is a pure "
                         "overlay — a hit completes in 0 rounds "
                         "without occupying a lookup slot, misses "
                         "are bit-identical to the cache-off engine; "
                         "the soak loop probes it for READ-class "
                         "admissions and its write flush bumps the "
                         "invalidation epoch)")
    ap.add_argument("--admission",
                    choices=("none", "shed", "queue", "degrade"),
                    default="none",
                    help="serve mode: per-class token-bucket "
                         "admission policy (shed = drop over-quota "
                         "requests and count them — overload no "
                         "longer exits 2; queue = wait for tokens; "
                         "degrade = answer over-quota hot keys from "
                         "the result cache only, shed the rest)")
    ap.add_argument("--admit-rate", type=float, default=0.0,
                    help="serve mode: token-bucket refill rate per "
                         "request class (req/s; required when "
                         "--admission is not 'none')")
    ap.add_argument("--admit-burst", type=float, default=None,
                    help="serve mode: token-bucket burst ceiling "
                         "(default: one second of --admit-rate)")
    ap.add_argument("--admit-key-rate", type=float, default=None,
                    help="serve mode: PER-KEY token-bucket refill "
                         "rate (req/s) layered under the class "
                         "buckets — one hot key's flood dies at its "
                         "own bucket instead of starving cold keys "
                         "of class tokens (the key map is LRU-capped "
                         "at --admit-max-keys)")
    ap.add_argument("--admit-max-keys", type=int, default=4096,
                    help="serve mode: per-key bucket map cap (LRU "
                         "eviction past this many distinct keys)")
    ap.add_argument("--key-pool", type=int, default=4096,
                    help="serve mode: distinct-key universe the "
                         "Zipf-popular request keys draw from")
    ap.add_argument("--serve-burst", type=int, default=2,
                    help="serve mode: rounds dispatched between "
                         "admission/harvest syncs")
    ap.add_argument("--serve-engine", choices=("burst", "resident"),
                    default="burst",
                    help="serve mode: loop architecture — 'burst' is "
                         "the per-burst admit/step/harvest host loop; "
                         "'resident' fuses admit→rounds→harvest into "
                         "ONE device program per macro step with a "
                         "device admission ring, drained double-"
                         "buffered (the steady state's only host sync "
                         "overlaps the next macro's compute)")
    ap.add_argument("--resident-rounds", type=int, default=2,
                    help="resident engine: rounds per macro step (the "
                         "resident analogue of --serve-burst; the "
                         "in-jit loop early-exits when every slot "
                         "drains, so overshoot is cheap)")
    ap.add_argument("--ring-slots", type=int, default=0,
                    help="resident engine: device admission-ring rows "
                         "(0 = the engine default, 4 x admit cap; "
                         "must be >= 2 x admit cap)")
    ap.add_argument("--resident-orch-budget", type=float, default=1.0,
                    help="resident engine: host-orchestration budget "
                         "recorded in the artifact — check_trace "
                         "fails the run if the host share of the "
                         "serve wall exceeds it (the gate legs pass "
                         "0.05; the default 1.0 records without "
                         "gating, for smoke shapes where a trickle "
                         "arrival rate is host-dominated by "
                         "construction)")
    ap.add_argument("--rung-select", type=int, default=0,
                    help="resident engine: in-jit width-ladder rung "
                         "block (0 = off — full-width merges; e.g. 8 "
                         "re-measures the PR-14 switch verdict INSIDE "
                         "the resident loop, where per-round host "
                         "dispatch no longer applies; bit-identical "
                         "results either way)")
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="serve mode: per-request latency SLO target "
                         "for the gauge set (milliseconds)")
    ap.add_argument("--serve-out", metavar="FILE", default=None,
                    help="serve mode: dump the serve artifact "
                         "(lifecycle conservation, latency histogram + "
                         "bucket-derived quantiles, SLO gauges) as "
                         "JSON — validated by tools/check_trace.py, "
                         "gated by tools/check_bench.py")
    ap.add_argument("--sweeps", type=int, default=12,
                    help="monitor mode: total monitoring sweeps "
                         "(sweep 0 is the initial full crawl; each "
                         "later sweep kills --kill-frac of the "
                         "remaining nodes first)")
    ap.add_argument("--monitor-period", type=int, default=4,
                    help="monitor mode: hard refresh bound — every "
                         "keyspace bucket is probed at least once per "
                         "this many sweeps (phase-jittered)")
    ap.add_argument("--fresh-ttl", type=int, default=2,
                    help="monitor mode: node age (sweeps since last "
                         "sighting) past which it counts toward its "
                         "bucket's staleness deficit")
    ap.add_argument("--stale-threshold", type=float, default=0.25,
                    help="monitor mode: bucket staleness-deficit "
                         "fraction that triggers an early re-probe")
    ap.add_argument("--miss-limit", type=int, default=2,
                    help="monitor mode: consecutive missed probes "
                         "before a tracked node is presumed dead")
    ap.add_argument("--outage-frac", type=float, default=0.0,
                    help="monitor mode: additionally kill this "
                         "fraction of nodes as ONE contiguous sorted-"
                         "id range at the mid-run sweep (a localized "
                         "keyspace outage — the deficit trigger must "
                         "catch it ahead of the periodic refresh)")
    ap.add_argument("--entries", type=int, default=8192,
                    help="index mode: entries inserted into the "
                         "secondary index (Zipf-keyed over "
                         "--key-pool ranks; per-key multiplicity "
                         "capped at the 16-entry leaf rule)")
    ap.add_argument("--scans", type=int, default=64,
                    help="index mode: range queries per timed pass")
    ap.add_argument("--scan-span", type=int, default=64,
                    help="index mode: width of each range in key "
                         "ranks")
    ap.add_argument("--index-out", metavar="FILE", default=None,
                    help="index mode: dump the trie/scan artifact "
                         "(kind swarm_index_trace: leaf occupancy, "
                         "split accounting, probe-round bound, exact "
                         "recall vs the sequential host-PHT oracle) "
                         "as JSON — validated by "
                         "tools/check_trace.py, gated by "
                         "tools/check_bench.py")
    ap.add_argument("--monitor-out", metavar="FILE", default=None,
                    help="monitor mode: dump the swarm-health "
                         "artifact (per-sweep records, freshness "
                         "conservation counters, detection lags, hop-"
                         "histogram-vs-analytic-model fidelity, "
                         "Poisson density profile) as JSON — "
                         "validated by tools/check_trace.py, gated by "
                         "tools/check_bench.py")
    ap.add_argument("--mix", choices=("read-heavy", "write-heavy",
                                      "scan-heavy", "chunk-heavy"),
                    default="read-heavy",
                    help="soak mode: scenario mix preset — the "
                         "write/scan/chunk fractions of the arrival "
                         "stream (read-heavy: 5%% writes; "
                         "write-heavy: 50%% writes; scan-heavy: 5%% "
                         "writes + 20%% index range scans; "
                         "chunk-heavy: 5%% writes + 20%% chunked-"
                         "value station ops); --write-frac/"
                         "--scan-frac/--chunk-frac override the "
                         "preset")
    ap.add_argument("--write-frac", type=float, default=None,
                    help="soak mode: fraction of arrivals that are "
                         "writes (announce with bumped seq), "
                         "overriding --mix; must be in [0, 1] with "
                         "write + scan <= 1")
    ap.add_argument("--scan-frac", type=float, default=None,
                    help="soak mode: fraction of arrivals that are "
                         "index range scans (the PR-10 trie engine in "
                         "the same arrival stream), overriding --mix")
    ap.add_argument("--soak-interval", type=float, default=0.5,
                    help="soak mode: timeline interval width in "
                         "seconds (both A/B arms use it — the unit "
                         "of every conservation row and interference "
                         "attribution)")
    ap.add_argument("--repub-period", type=float, default=1.0,
                    help="soak mode: seconds between the end of one "
                         "republish sweep and the begin of the next")
    ap.add_argument("--monitor-gap", type=float, default=0.0,
                    help="soak mode: seconds between monitor sweeps "
                         "(0 = continuous crawling)")
    ap.add_argument("--maint-cap", type=int, default=256,
                    help="soak mode: maintenance rows admitted into "
                         "free slots per loop iteration at most")
    ap.add_argument("--maint-slot-frac", type=float, default=0.25,
                    help="soak mode: hard ceiling on the fraction of "
                         "serve slots maintenance may occupy at once "
                         "(the admission reserve keeping a crawl from "
                         "crowding the slot plane)")
    ap.add_argument("--monitor-bootstrap", action="store_true",
                    help="soak mode: run the monitor's initial full "
                         "crawl CLOSED-LOOP at setup (a joining "
                         "node's bootstrap crawl, the PR-8 path) so "
                         "the interleaved sweeps are the steady-state "
                         "incremental ones — the 1M acceptance shape, "
                         "where a full grid sweep through the slot "
                         "plane outlasts the serve horizon")
    ap.add_argument("--churn-every", type=float, default=1.0,
                    help="soak mode: seconds between churn events "
                         "(each kills --kill-frac of live nodes, "
                         "then heals routing tables); 0 disables")
    ap.add_argument("--slo-violation-max", type=float, default=0.10,
                    help="soak mode: the SLO violation-ratio bound "
                         "the artifact states and check_trace gates "
                         "the measured ratio against")
    ap.add_argument("--interference", choices=("on", "off"),
                    default="on",
                    help="soak mode: run the maintenance-off A/B arm "
                         "on the same arrival schedule and emit the "
                         "interference ledger (off = single arm, no "
                         "ledger — smoke runs only)")
    ap.add_argument("--soak-out", metavar="FILE", default=None,
                    help="soak mode: dump the swarm_soak_trace "
                         "artifact (per-interval timeline, lifecycle "
                         "conservation per work class, interference "
                         "ledger, monitor + republish blocks, SLO "
                         "gauges) as JSON — validated by "
                         "tools/check_trace.py, gated by "
                         "tools/check_bench.py")
    ap.add_argument("--chunk-parts", type=int, default=4,
                    help="chunked mode: parts per value (each a "
                         "--payload-words slot row; the chaos legs "
                         "need >= 2 so a write can tear between "
                         "parts); soak mode: parts per chunk-station "
                         "value when --chunk-frac > 0")
    ap.add_argument("--chunk-fault-drop-frac", type=float,
                    default=0.25,
                    help="chunked mode: fraction of values whose part "
                         "0 is dropped at announce (the torn_drop "
                         "leg's part_drop_mask); must be in (0, 1] — "
                         "a leg that tears nothing gates nothing")
    ap.add_argument("--chunk-fault-kill-part", type=int, default=None,
                    help="chunked mode: the part index the mid-"
                         "announce kill strikes at (parts >= this "
                         "never leave the NIC; default parts/2, must "
                         "be in [1, parts))")
    ap.add_argument("--chunk-fault-forge-part", type=int, default=1,
                    help="chunked mode: the part whose first word the "
                         "forge leg bit-flips (must be in [0, parts))")
    ap.add_argument("--chunk-frac", type=float, default=None,
                    help="soak mode: fraction of arrivals that are "
                         "chunked-value station ops (reads + "
                         "same-bytes refresh writes of multi-part "
                         "values through the routed-twin store), "
                         "overriding --mix; write + scan + chunk "
                         "must stay <= 1")
    ap.add_argument("--chunk-write-frac", type=float, default=0.25,
                    help="soak mode: fraction of chunk-station ops "
                         "that are seq-bump refresh WRITES (the rest "
                         "are byte-exact reads); must be in [0, 1]")
    ap.add_argument("--chunked-out", metavar="FILE", default=None,
                    help="chunked mode: dump the chunk-fault artifact "
                         "(kind swarm_chunked_trace: per-leg part-"
                         "summed StoreTrace conservation vs the "
                         "whole-value oracle, defended-vs-undefended "
                         "integrity curve, torn-reads-as-missing "
                         "rate, get-merge root rejections, republish-"
                         "heal sweeps) as JSON — validated by "
                         "tools/check_trace.py, gated by "
                         "tools/check_bench.py")
    ap.add_argument("--auth-out", metavar="FILE", default=None,
                    help="auth mode: dump the integrity artifact "
                         "(per-leg StoreTrace conservation, defended-"
                         "vs-undefended integrity curve, verify "
                         "overhead A/B, pipelined-signature stats) as "
                         "JSON — validated by tools/check_trace.py, "
                         "gated by tools/check_bench.py")
    ap.add_argument("--auth-overhead-budget", type=float, default=0.10,
                    help="auth mode: stated ceiling on the on-device "
                         "verify overhead ratio (verified vs "
                         "unverified announce+get wall; the checker "
                         "holds the measured ratio to it)")
    args = ap.parse_args()

    # Fault fractions are probabilities: reject out-of-range values
    # LOUDLY at the CLI boundary.  (jax.random.uniform comparisons
    # against e.g. kill_frac=1.5 or -0.2 silently behave like 1.0/0.0,
    # and a bench that "ran fine" on a nonsense fault schedule is a
    # lie in the artifact record.)
    for frac_name in ("kill_frac", "drop_frac", "byzantine_frac",
                      "outage_frac"):
        v = getattr(args, frac_name)
        if v is not None and not 0.0 <= v <= 1.0:
            ap.error(f"--{frac_name.replace('_', '-')} must be a "
                     f"fraction in [0, 1], got {v}")
    if args.byzantine_frac is None:
        # Per-mode default: the chaos-lookup grid keeps its historical
        # 0.05; the monitor watches an honest swarm unless asked.
        args.byzantine_frac = 0.05 if args.mode == "chaos-lookup" \
            else 0.0
    if args.mode in ("monitor", "soak"):
        # Soak consumes the monitor knobs too (its interleaved sweeps
        # are MonitorEngine sweeps): invalid values must fail at this
        # boundary, not deep inside the engine.
        if args.sweeps < 1:
            ap.error(f"--sweeps must be >= 1, got {args.sweeps}")
        if args.monitor_period < 1:
            ap.error(f"--monitor-period must be >= 1, got "
                     f"{args.monitor_period}")
        if args.miss_limit < 1:
            ap.error(f"--miss-limit must be >= 1, got "
                     f"{args.miss_limit}")
        if args.fresh_ttl < 0:
            ap.error(f"--fresh-ttl must be >= 0, got {args.fresh_ttl}")
        if not 0.0 <= args.stale_threshold <= 1.0:
            ap.error(f"--stale-threshold must be a fraction in [0, 1],"
                     f" got {args.stale_threshold}")

    if args.mode in ("serve", "soak"):
        # Serve/soak-arg validation at the CLI boundary (the satellite
        # contract): rates/durations are physical quantities — a ≤0
        # value or an uncapped duration must fail HERE, loudly, not as
        # a shape crash or a gate-timeout three layers down.  Soak
        # reuses the serve path verbatim: its open loop IS the serve
        # loop plus maintenance.
        if args.arrival_rate <= 0:
            ap.error(f"--arrival-rate must be > 0 req/s, got "
                     f"{args.arrival_rate}")
        if args.duration <= 0:
            ap.error(f"--duration must be > 0 s, got {args.duration}")
        if args.duration > 120:
            ap.error(f"--duration {args.duration}s exceeds the 120 s "
                     f"{args.mode} cap (the tier-1 gate runs under a "
                     f"870 s timeout; a longer open-loop run cannot "
                     f"fit a gate leg — split it into repeats)")
        if args.serve_slots == "auto":
            if args.mode != "serve":
                ap.error("--serve-slots auto is a serve-mode knob "
                         "(the soak slot plane is sized explicitly)")
        elif args.serve_slots < 8:
            ap.error(f"--serve-slots must be >= 8, got "
                     f"{args.serve_slots}")
        if args.serve_cache < 0:
            ap.error(f"--serve-cache must be >= 0, got "
                     f"{args.serve_cache}")
        if args.admission != "none" and args.admit_rate <= 0:
            ap.error(f"--admission {args.admission} requires "
                     f"--admit-rate > 0 req/s, got {args.admit_rate}")
        if args.admit_burst is not None and args.admit_burst < 1:
            ap.error(f"--admit-burst must be >= 1 token, got "
                     f"{args.admit_burst}")
        if args.admit_key_rate is not None:
            if args.admission == "none":
                ap.error("--admit-key-rate needs an --admission "
                         "policy (the key buckets gate the same "
                         "admission step)")
            if args.admission == "queue":
                ap.error("--admit-key-rate is incompatible with "
                         "--admission queue: queue is head-of-line, "
                         "so a key-dry head would block every "
                         "request behind it — use shed or degrade")
            if args.admit_key_rate <= 0:
                ap.error(f"--admit-key-rate must be > 0 req/s, got "
                         f"{args.admit_key_rate}")
        if args.admit_max_keys < 1:
            ap.error(f"--admit-max-keys must be >= 1, got "
                     f"{args.admit_max_keys}")
        if args.admission == "degrade" and not args.serve_cache:
            ap.error("--admission degrade answers from the result "
                     "cache — set --serve-cache > 0")
        if args.sharded and args.mode != "serve":
            ap.error("--sharded is a serve-mode knob (sharded lookup "
                     "benches are --mode sharded)")
        if args.mode != "serve":
            # The admission-policy knobs must not be silently ignored
            # (--serve-cache is shared: the soak loop's probe-fused
            # admission consults the cache since round 17).
            if args.admission != "none":
                ap.error("--admission/--admit-rate are serve-mode "
                         "knobs")
        if args.sharded and args.serve_slots == "auto":
            ap.error("--serve-slots auto probes the LOCAL engine's "
                     "round wall, which under-sizes the mesh plane "
                     "(routed rounds pay collectives) — size "
                     "--serve-slots explicitly with --sharded")
        if args.key_pool < 1:
            ap.error(f"--key-pool must be >= 1, got {args.key_pool}")
        if args.serve_burst < 1:
            ap.error(f"--serve-burst must be >= 1, got "
                     f"{args.serve_burst}")
        if args.slo_ms <= 0:
            ap.error(f"--slo-ms must be > 0, got {args.slo_ms}")
        if args.zipf is not None and args.zipf < 0:
            ap.error(f"--zipf must be >= 0, got {args.zipf}")
    if args.mode == "soak":
        # Scenario-mix fractions are probabilities over the arrival
        # stream: presets resolve first, explicit flags override, and
        # anything outside [0, 1] (or a mix that sums past 1) fails
        # HERE instead of as a nonsense schedule in the artifact.
        preset = {"read-heavy": (0.05, 0.0, 0.0),
                  "write-heavy": (0.50, 0.0, 0.0),
                  "scan-heavy": (0.05, 0.20, 0.0),
                  "chunk-heavy": (0.05, 0.0, 0.20)}[args.mix]
        if args.write_frac is None:
            args.write_frac = preset[0]
        if args.scan_frac is None:
            args.scan_frac = preset[1]
        if args.chunk_frac is None:
            args.chunk_frac = preset[2]
        for nm in ("write_frac", "scan_frac", "chunk_frac",
                   "chunk_write_frac"):
            v = getattr(args, nm)
            if not 0.0 <= v <= 1.0:
                ap.error(f"--{nm.replace('_', '-')} must be a "
                         f"fraction in [0, 1], got {v}")
        if args.write_frac + args.scan_frac + args.chunk_frac > 1.0:
            ap.error(f"scenario mix over-full: write {args.write_frac}"
                     f" + scan {args.scan_frac} + chunk "
                     f"{args.chunk_frac} > 1")
        if args.soak_interval <= 0:
            ap.error(f"--soak-interval must be > 0 s, got "
                     f"{args.soak_interval}")
        if args.repub_period < 0 or args.monitor_gap < 0 \
                or args.churn_every < 0:
            ap.error("--repub-period/--monitor-gap/--churn-every "
                     "must be >= 0")
        if args.maint_cap < 1:
            ap.error(f"--maint-cap must be >= 1, got {args.maint_cap}")
        if not 0.0 < args.maint_slot_frac <= 1.0:
            ap.error(f"--maint-slot-frac must be in (0, 1], got "
                     f"{args.maint_slot_frac}")
        if not 0.0 < args.slo_violation_max <= 1.0:
            ap.error(f"--slo-violation-max must be in (0, 1], got "
                     f"{args.slo_violation_max}")
    if args.zipf is None and args.mode == "index":
        # Read-heavy scans over a skewed index (arXiv:1009.3681's
        # workload shape): hot keys hold multiple entries, hot ranges
        # get scanned more.
        args.zipf = 1.2
    if args.zipf is None and args.mode not in ("serve", "soak"):
        # Non-serve modes keep their historical default (uniform for
        # churn, the 1.2 hotshard fallback keys off 0).
        args.zipf = 0.0
    if args.mode == "index":
        if args.entries < 1:
            ap.error(f"--entries must be >= 1, got {args.entries}")
        if args.scans < 1:
            ap.error(f"--scans must be >= 1, got {args.scans}")
        if args.scan_span < 1:
            ap.error(f"--scan-span must be >= 1, got {args.scan_span}")
        if args.key_pool < 2:
            ap.error(f"--key-pool must be >= 2, got {args.key_pool}")
    if args.mode == "auth":
        if not 0.0 < args.auth_overhead_budget <= 0.10:
            # The acceptance contract caps the statable budget: a
            # budget loose enough to gate nothing must fail HERE.
            ap.error(f"--auth-overhead-budget must be in (0, 0.10], "
                     f"got {args.auth_overhead_budget}")
        if not args.payload_words:
            args.payload_words = 8     # content-addressing needs bytes
    if args.mode in ("chunked", "soak"):
        # Chunk knobs are part indices and probabilities: reject
        # nonsense at the CLI boundary, mirroring the --mix rule — a
        # fault schedule that tears nothing (or tears out of range)
        # gates nothing and lies in the artifact record.
        if not 2 <= args.chunk_parts <= 16:
            ap.error(f"--chunk-parts must be in [2, 16] (a chunk "
                     f"fault needs a part boundary to tear at), got "
                     f"{args.chunk_parts}")
    if args.mode == "chunked":
        if not 0.0 < args.chunk_fault_drop_frac <= 1.0:
            ap.error(f"--chunk-fault-drop-frac must be in (0, 1], "
                     f"got {args.chunk_fault_drop_frac}")
        if args.chunk_fault_kill_part is None:
            args.chunk_fault_kill_part = max(1, args.chunk_parts // 2)
        if not 1 <= args.chunk_fault_kill_part < args.chunk_parts:
            ap.error(f"--chunk-fault-kill-part must be in [1, "
                     f"{args.chunk_parts}) — killing before part 0 "
                     f"announces nothing, at or past the last part "
                     f"tears nothing, got "
                     f"{args.chunk_fault_kill_part}")
        if not 0 <= args.chunk_fault_forge_part < args.chunk_parts:
            ap.error(f"--chunk-fault-forge-part must be in [0, "
                     f"{args.chunk_parts}), got "
                     f"{args.chunk_fault_forge_part}")
        if not args.payload_words:
            args.payload_words = 2     # parts are W-word slot rows
    if args.kill_frac is None:
        args.kill_frac = {"chaos-lookup": 0.10,
                          "monitor": 0.05,
                          "auth": 0.10,
                          "chunked": 0.10,
                          "soak": 0.02}.get(args.mode, 0.5)
    if args.nodes is None:
        args.nodes = {"churn": 100_000, "sharded": 1_000_000,
                      "hotshard": 1_000_000,
                      "repub": 65_536,
                      "chaos": 65_536,
                      "repub-profile": 65_536,
                      "serve": 65_536,
                      "soak": 65_536,
                      "auth": 65_536,
                      "chunked": 8_192,
                      "monitor": 1_000_000,
                      "index": 1_000_000,
                      "chaos-lookup": 1_000_000}.get(args.mode,
                                                     10_000_000)
    if args.ledger_out and args.mode == "lookups" \
            and args.compact == "off":
        # The ledger's round table cross-checks against
        # round_wall_p50, which only the compacted dispatcher's burst
        # clocks produce.
        ap.error("--ledger-out requires the compacted dispatcher in "
                 "lookups mode (drop --compact off)")
    if args.mode == "auth":
        return auth_main(args)
    if args.mode == "chunked":
        return chunked_main(args)
    if args.mode == "soak":
        return soak_main(args)
    if args.mode == "monitor":
        return monitor_main(args)
    if args.mode == "index":
        return index_main(args)
    if args.mode == "serve":
        return serve_main(args)
    if args.mode == "chaos-lookup":
        return chaos_lookup_main(args)
    if args.mode == "repub-profile":
        return repub_profile_main(args)
    if args.mode == "putget":
        return putget_main(args)
    if args.mode == "churn":
        return churn_main(args)
    if args.mode == "crawl":
        return crawl_main(args)
    if args.mode == "sharded":
        return sharded_main(args)
    if args.mode == "hotshard":
        return hotshard_main(args)
    if args.mode == "repub":
        return repub_main(args)
    if args.mode == "chaos":
        return chaos_main(args)

    from opendht_tpu.models.swarm import (
        SwarmConfig, build_swarm, lookup, merge_traces,
        resolve_merge_impl, traced_lookup, true_closest,
    )

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    key = jax.random.PRNGKey(0)
    swarm = build_swarm(key, cfg)
    _ = np.asarray(swarm.tables[:1, :1])   # force build

    targets = jax.random.bits(jax.random.PRNGKey(1), (args.lookups, 5),
                              jnp.uint32)
    if not args.lookup_batch and args.nodes >= 4_000_000:
        # Big-table swarms: the per-step response/merge temps scale
        # with L, and next to a ~10 GB table a full 1M-lookup batch
        # OOMs; ~500k chunks keep peak HBM in budget (measured best:
        # 359.7k lookups/s vs 277k at 250k chunks).
        args.lookup_batch = even_chunk_size(args.lookups, 524_288)
    lb = args.lookup_batch or args.lookups
    chunks = [targets[lo:lo + lb] for lo in range(0, args.lookups, lb)]

    def sync(res):
        # A value fetch is the only reliable completion barrier in the
        # remote-tunnel dev environment (block_until_ready can return
        # before remote execution finishes); an 8-byte scalar that
        # depends on the full result forces it without paying the
        # multi-MB array transfer inside the timed region.
        return int(np.asarray(jnp.sum(res.found[:, 0])))

    # Flight recorder: the traced engine is seed-identical to the plain
    # one (the trace is a pure observer), so with --trace-out the TIMED
    # runs themselves run traced — the reported rate includes capture
    # cost, keeping the <=5% overhead budget honest.
    use_trace = bool(args.trace_out)
    compact = args.compact != "off"
    # Lifecycle A/B knob: the tracked engine must stay bit-identical
    # (tests) and within the <=5% budget on this leg (BASELINE.md).
    track = bool(args.track_lifecycle)
    traces = []
    chunk_stats = []

    def run_all(seed):
        chunk_stats[:] = [dict() for _ in chunks] if compact else []
        sd = lambda i: chunk_stats[i] if compact else None
        if use_trace:
            pairs = [traced_lookup(swarm, cfg, c,
                                   jax.random.PRNGKey(seed + i),
                                   compact=compact, stats=sd(i),
                                   track_lifecycle=track)
                     for i, c in enumerate(chunks)]
            rs = [p[0] for p in pairs]
            traces[:] = [p[1] for p in pairs]
        else:
            rs = [lookup(swarm, cfg, c, jax.random.PRNGKey(seed + i),
                         compact=compact, stats=sd(i),
                         track_lifecycle=track)
                  for i, c in enumerate(chunks)]
        for r in rs:
            sync(r)
        return rs

    ress = run_all(2)  # warmup/compile

    if args.profile:
        with jax.profiler.trace(args.profile):
            run_all(99)

    times = []
    for r in range(args.repeat):
        t0 = time.perf_counter()
        ress = run_all(300 + 100 * r)
        times.append(time.perf_counter() - t0)
    dt = min(times)
    lps = args.lookups / dt

    res = LookupResultConcat(ress)
    hops = np.asarray(res.hops)

    # Phase attribution (round-9 satellite): ONE extra UNTIMED pass
    # with block_until_ready barriers between init / loop / finalize
    # (the barriers de-pipeline the device queue, so attribution never
    # rides — or perturbs — the timed runs above), plus per-round wall
    # estimates from the burst clocks (burst wall / rounds-in-burst;
    # rounds inside a burst pipeline with no sync, so that quotient is
    # the honest per-round figure).
    phase, round_p50 = None, None
    attr_compile_count = None
    if compact:
        pstats = [dict(time_phases=True) for _ in chunks]
        # Reuse whichever engine the timed runs already compiled (the
        # traced one under --trace-out): attribution must not pay a
        # fresh jit of the other engine's step and book it as loop
        # time.  The SEED is reused too (the last timed run's): ladder
        # widths follow the seed's convergence curve, so a fresh seed
        # here could shrink to a width the timed seeds never reached
        # and book that step's compile inside a burst clock —
        # round_wall_p50 would silently include a compile.  Replaying
        # the last timed seed replays its exact width ladder; the
        # step-jit cache-size delta below asserts nothing compiled
        # (the ledger's compile-count field).
        from opendht_tpu.obs.ledger import step_cache_size
        attr_seed = 300 + 100 * (args.repeat - 1)
        cache0 = step_cache_size()
        if use_trace:
            rs = [traced_lookup(swarm, cfg, c,
                                jax.random.PRNGKey(attr_seed + i),
                                compact=True, stats=pstats[i],
                                track_lifecycle=track)[0]
                  for i, c in enumerate(chunks)]
        else:
            rs = [lookup(swarm, cfg, c,
                         jax.random.PRNGKey(attr_seed + i),
                         compact=True, stats=pstats[i],
                         track_lifecycle=track)
                  for i, c in enumerate(chunks)]
        for r in rs:
            sync(r)
        attr_compile_count = step_cache_size() - cache0
        if attr_compile_count:
            # Report, don't abort: the timed numbers above are already
            # in hand and the field rides the row + ledger artifact,
            # where check_trace rejects any non-zero value — that gate
            # is the enforcement, not a crash that discards the run.
            print(f"bench: WARNING — {attr_compile_count} step jit(s) "
                  f"compiled inside the clocked attribution pass; "
                  f"round_wall_p50 may include compile time "
                  f"(check_trace rejects the artifact)",
                  file=sys.stderr)
        per_round = [wall / n for s in pstats
                     for wall, n in s.get("burst_walls", ())
                     for _ in range(n)]
        # Full-width rounds only (each chunk's FIRST burst, before the
        # ladder shrinks): the apples-to-apples target for the ledger's
        # full-width sub-phase table — comparing that table against the
        # all-rounds p50 would book the ladder's savings as attribution
        # drift at small configs.
        full_round = [wall / n for s in pstats
                      for wall, n in s.get("burst_walls", ())[:1]
                      for _ in range(n)]
        phase = {
            "init_s": round(sum(s["init_s"] for s in pstats), 4),
            "loop_s": round(sum(s["loop_s"] for s in pstats), 4),
            "finalize_s": round(sum(s["finalize_s"] for s in pstats),
                                4),
            "total_s": round(sum(s["phase_total_s"] for s in pstats),
                             4),
        }
        if per_round:
            round_p50 = round(float(np.percentile(per_round, 50)), 5)
        round_full_p50 = (round(float(np.percentile(full_round, 50)), 5)
                          if full_round else None)

    # Cost ledger (round-10 tentpole): one instrumented replay of the
    # last timed seed with execution barriers — per-kernel walls/calls,
    # XLA cost_analysis FLOPs/bytes, donation status, HBM watermarks —
    # plus the round sub-phase A/B table (alpha-select / gather /
    # window-decode / merge / scatter-writeback prefixes whose rows
    # telescope to the fused round).  Runs strictly AFTER every timed
    # number is in hand: the barriers serialize the device queue.
    ledger = None
    if args.ledger_out:
        from opendht_tpu.obs.ledger import (CostLedger,
                                            measure_round_phases)
        ledger = CostLedger()
        # run_all rebinds traces[]/chunk_stats[] — the artifact's trace
        # and the dispatch-attribution fields must come from the TIMED
        # runs, not this replay, so snapshot and restore around it.
        saved_traces, saved_stats = list(traces), list(chunk_stats)
        with ledger.instrument(barrier=True):
            run_all(300 + 100 * (args.repeat - 1))
        traces[:], chunk_stats[:] = saved_traces, saved_stats
        ledger.sample_hbm()
        phases = measure_round_phases(
            swarm, cfg, chunks[0], jax.random.PRNGKey(77),
            repeats=max(2, args.repeat))
        phases["round_wall_p50"] = round_full_p50 or round_p50
        ledger.round_phases = phases
        ledger.attr_compile_count = attr_compile_count
        # Round-18 width-ladder attribution: advance a probe batch to
        # a TAIL-round state (where the live-slot watermark actually
        # shrinks), pick the rung the burst loop would, and price the
        # same telescoping prefixes with the merge laddered —
        # prefix-equivalence asserted inside measure_round_phases, the
        # table validated by check_trace (self-consistent against its
        # own fused-round wall; the full-width table above keeps the
        # round_wall_p50 cross-check).
        if resolve_merge_impl(cfg) == "xla":
            from opendht_tpu.models.swarm import (_pending_and_wneed,
                                                  _sample_origins,
                                                  lookup_init,
                                                  lookup_step)
            from opendht_tpu.ops.xor_metric import pick_merge_width
            resp_w = cfg.alpha * 2 * cfg.bucket_k
            # Same key + targets as the attribution pass below, so the
            # probe's state evolution (and hence the rung chosen at
            # round `adv`) is EXACTLY the state the laddered table
            # measures — a rung probed on a different trajectory could
            # overflow there and silently price the guard's full
            # branch.
            pst = lookup_init(swarm, cfg, chunks[0], _sample_origins(
                jax.random.PRNGKey(77), swarm.alive,
                chunks[0].shape[0]))
            rung, adv = None, 0
            for r in range(cfg.max_steps):
                pst = lookup_step(swarm, cfg, pst)
                wneed = int(jax.device_get(
                    _pending_and_wneed(pst, cfg)[1]))
                if wneed == 0:
                    break
                rung = pick_merge_width(wneed, resp_w,
                                        2 * cfg.bucket_k)
                if rung is not None:
                    adv = r + 1
                    break
            if rung is not None:
                ledger.round_phases_laddered = measure_round_phases(
                    swarm, cfg, chunks[0], jax.random.PRNGKey(77),
                    repeats=max(2, args.repeat), merge_w=rung,
                    advance_rounds=adv)

    # Tier-2 attribution: where the fused Pallas round kernel is the
    # resolved hot path (TPU), also time the XLA rank-merge variant so
    # the BENCH row reports the Pallas-vs-XLA delta on the same
    # machine.  Never runs off-TPU (auto resolves to the rank merge
    # there, and interpret-mode Pallas must stay off hot paths).
    merge_impl = resolve_merge_impl(cfg)
    pallas_delta = None
    if merge_impl == "pallas":
        cfg_x = cfg._replace(merge_impl="xla")

        def run_xla(seed):
            # Same engine as the timed runs (traced under --trace-out):
            # the A/B must compare like with like, or the recorder's
            # capture cost would bias the reported delta.
            if use_trace:
                rs = [traced_lookup(swarm, cfg_x, c,
                                    jax.random.PRNGKey(seed + i),
                                    compact=compact,
                                    track_lifecycle=track)[0]
                      for i, c in enumerate(chunks)]
            else:
                rs = [lookup(swarm, cfg_x, c,
                             jax.random.PRNGKey(seed + i),
                             compact=compact, track_lifecycle=track)
                      for i, c in enumerate(chunks)]
            for r in rs:
                sync(r)

        run_xla(2)
        tx = []
        for i in range(max(1, args.repeat - 1)):
            t0 = time.perf_counter()
            run_xla(700 + 100 * i)
            tx.append(time.perf_counter() - t0)
        pallas_delta = {"xla_merge_wall_s": round(min(tx), 4),
                        "pallas_vs_xla_speedup": round(min(tx) / dt, 3)}

    # Recall on a subsample (exact k-closest over the full matrix is
    # O(L·N); sample keeps it cheap).  Recall is an auxiliary metric:
    # any failure here (e.g. a kernel config that fails to compile at
    # the ground-truth shape) must not zero out the primary number —
    # that is exactly how rounds 1 and 2 shipped rc=1 benches.
    recall, recall_error = None, None
    try:
        m = min(args.recall_sample, args.lookups)
        sample_t = targets[:m]
        truth = np.asarray(true_closest(swarm, cfg, sample_t, k=8))
        found = np.asarray(res.found[:m])
        match = ((truth[:, :, None] == found[:, None, :])
                 & (truth[:, :, None] >= 0))
        recall = float(match.any(axis=2).mean())
    except Exception as e:  # noqa: BLE001 — report, never crash the bench
        recall_error = f"{type(e).__name__}: {e}"[:300]

    out = {
        "metric": "swarm_lookups_per_sec",
        "value": round(lps, 1),
        "unit": "lookups/s",
        "vs_baseline": round(lps / REFERENCE_LOOKUPS_PER_SEC, 2),
        "baseline_note": "vs our measured Python reimplementation of "
                         "the reference architecture (140 lookups/s, "
                         "BASELINE.md; C++ reference unbuildable here, "
                         "publishes no numbers)",
        "n_nodes": args.nodes,
        "n_lookups": args.lookups,
        "wall_s": round(dt, 4),
        # Steady-state spread over the --repeat runs (warm-up always
        # excluded): p95 ≈ p50 means no compile/GC straggler polluted
        # the sample the best-of came from.
        "wall_p50": round(float(np.percentile(times, 50)), 4),
        "wall_p95": round(float(np.percentile(times, 95)), 4),
        "median_hops": float(np.median(hops)),
        "done_frac": float(np.asarray(res.done).mean()),
        "recall_at_8": round(recall, 4) if recall is not None else None,
        "compact": compact,
        "merge_impl": merge_impl,
        "track_lifecycle": track,
        "platform": jax.devices()[0].platform,
    }
    if phase is not None:
        out["phase_wall"] = phase
    if round_p50 is not None:
        out["round_wall_p50"] = round_p50
    if compact and round_full_p50 is not None:
        out["round_wall_full_p50"] = round_full_p50
    if pallas_delta is not None:
        out.update(pallas_delta)
    if chunk_stats:
        # Dispatch attribution for the compaction ladder: how many
        # rounds actually ran and what fraction of the batch width they
        # were dispatched at — the denominator of the straggler win.
        rd = sum(s.get("rounds_dispatched", 0) for s in chunk_stats)
        rr = sum(s.get("dispatched_row_rounds", 0) for s in chunk_stats)
        full_rr = sum(s.get("rounds_dispatched", 0) * c.shape[0]
                      for s, c in zip(chunk_stats, chunks))
        out["rounds_dispatched"] = rd
        out["mean_active_frac"] = (round(rr / full_rr, 4)
                                   if full_rr else None)
        mws = sorted({mw for s in chunk_stats
                      for mw in s.get("merge_widths", ())})
        if mws:
            # Distinct merge-width rungs the round-18 ladder dispatched
            # (full width included) — the width-pruning attribution.
            out["merge_widths"] = mws
    if recall_error is not None:
        out["recall_error"] = recall_error
    if attr_compile_count is not None:
        out["attr_compile_count"] = attr_compile_count
    if ledger is not None:
        with open(args.ledger_out, "w") as f:
            json.dump(ledger.to_dict(bench_row=out), f)
            f.write("\n")
    if use_trace:
        dump_trace(args.trace_out, out, merge_traces(traces),
                   args.lookups, res.hops, cfg.max_steps)
    print(json.dumps(out))


def dump_trace(path, bench_row, trace, n_lookups, hops, max_steps):
    """Write the flight-recorder artifact: the BENCH row, the merged
    per-round trace, and the hop-count histogram — one JSON object,
    parseable by ``opendht_tpu.tools.check_trace`` (the gate leg)."""
    from opendht_tpu.models.swarm import hop_histogram, trace_to_dict

    hist = [int(v) for v in np.asarray(hop_histogram(hops, max_steps))]
    obj = {
        "kind": "swarm_lookup_trace",
        "bench": bench_row,
        "trace": trace_to_dict(trace, n_lookups),
        "hop_histogram": hist,
    }
    with open(path, "w") as f:
        json.dump(obj, f)
        f.write("\n")


def auto_slots(args, cfg):
    """Store slots per node for --slots 0 (auto).

    16 (the calibrated default) while HBM allows; at big N the
    ``[N, slots]`` store must share the chip with the routing table
    and the lookup transients, so slots scale down from what
    ``memory_stats()`` reports instead of relying on manual ``--slots``
    guidance at 10M nodes.
    """
    if args.slots:
        return args.slots
    from opendht_tpu.models.swarm import device_hbm_bytes, table_bytes

    # The bench always runs on a live device — initialize the backend
    # now so device_hbm_bytes() reads the real memory_stats() instead
    # of its conservative uninitialized-backend fallback.
    n_shards = max(1, len(jax.devices()))
    if not getattr(args, "mode", "") in ("sharded", "repub"):
        n_shards = 1          # local engine: whole state on one chip
    # Per-DEVICE shares: tables and the store shard over the node axis.
    n = cfg.n_nodes // n_shards
    table = table_bytes(cfg) // n_shards
    w = getattr(args, "payload_words", 0) or 0
    # keys 20 + five u32 scalars + used flag (+ payload words) per slot
    per_slot = n * (44 + 4 * w)
    # Slot-independent store state: listener tables (4 listen slots:
    # lkeys 80 B + lids 16 B) + cursors — ~1 GB at 10M nodes, NOT
    # negligible against the transient reserve.
    fixed = n * (4 * 24 + 8)
    # 3.5 GB transient reserve: measured — slots=3 at 10M (reserve 3.0)
    # OOMed the get's lookup bursts next to the 10.2 GB table.
    free = device_hbm_bytes() - table - 20 * cfg.n_nodes - fixed \
        - 3_500_000_000
    # 2× per slot: the runtime does no input-output aliasing through
    # the jit boundary, so every store-mutating op holds the slot
    # leaves TWICE (in + out) at its peak.
    return int(max(2, min(16, free // max(2 * per_slot, 1))))


def putget_main(args):
    """Full DHT round-trip: announce P values, then get them all.

    Exercises storage (onAnnounce/onGetValues scatter-gather), not just
    routing — the workload of the reference's persistence scenarios
    (python/tools/dht/tests.py:439-827).
    """
    from opendht_tpu.models.storage import (
        StoreConfig, announce, empty_store, get_values,
    )
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    if args.value_parts and not args.payload_words:
        args.payload_words = 4
    # listen_slots=1 at 10M: the put/get throughput bench registers no
    # listeners, and idle [N,4,...] listener tables cost ~1 GB next to
    # the 10.2 GB routing table (the listen path has its own tests and
    # dryrun assertions).
    scfg = StoreConfig(slots=auto_slots(args, cfg),
                       listen_slots=1 if args.nodes >= 4_000_000 else 4,
                       max_listeners=1 << 10,
                       payload_words=args.payload_words)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    jax.block_until_ready(swarm.tables)
    p = args.puts
    keys = jax.random.bits(jax.random.PRNGKey(1), (p, 5), jnp.uint32)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    seqs = jnp.ones((p,), jnp.uint32)
    payloads = (jax.random.bits(jax.random.PRNGKey(8),
                                (p, args.payload_words), jnp.uint32)
                if args.payload_words else None)

    if args.value_parts:
        return putget_chunked(args, cfg, scfg, swarm, keys, vals, seqs)

    def roundtrip(seed):
        store = empty_store(cfg.n_nodes, scfg)
        store, rep = announce(swarm, cfg, store, scfg, keys, vals, seqs,
                              0, jax.random.PRNGKey(seed),
                              payloads=payloads)
        res = get_values(swarm, cfg, store, scfg, keys,
                         jax.random.PRNGKey(seed + 1))
        return rep, res

    def sync(res):
        # Scalar fetch = the only honest completion barrier here (see
        # the lookups mode).
        return int(np.asarray(jnp.sum(res.val[:8])))

    rep, res = roundtrip(2)  # warmup/compile
    sync(res)

    times = []
    for r in range(args.repeat):
        t0 = time.perf_counter()
        rep, res = roundtrip(10 + 2 * r)
        sync(res)
        times.append(time.perf_counter() - t0)
    dt = min(times)

    out = {
        "metric": "swarm_putget_roundtrips_per_sec",
        "value": round(p / dt, 1),
        "unit": "put+get/s",
        "vs_baseline": round(p / dt / REFERENCE_LOOKUPS_PER_SEC, 2),
        "n_nodes": args.nodes,
        "n_puts": p,
        "slots": scfg.slots,
        "wall_s": round(dt, 4),
        "hit_rate": float(np.asarray(res.hit).mean()),
        "mean_replicas": float(np.asarray(rep.replicas).mean()),
        "median_hops": float(np.median(np.asarray(res.hops))),
        # Token-only stores hold uint32 value tokens + abstract sizes;
        # --payload-words attaches REAL bytes, verified below — see
        # BASELINE.md fidelity note.
        "sim_fidelity": ("payload-chunks" if args.payload_words
                         else "token-values"),
        "platform": jax.devices()[0].platform,
    }
    if args.payload_words:
        hit = np.asarray(res.hit)
        ok = (np.asarray(res.payload)[hit]
              == np.asarray(payloads)[hit]).all()
        out["payload_bytes"] = 4 * args.payload_words
        out["payloads_intact"] = bool(ok)
    print(json.dumps(out))


def putget_chunked(args, cfg, scfg, swarm, keys, vals, seqs):
    """Variable-size value round-trips: random per-value byte lengths
    spanning 1..--value-parts fixed-width slots (models.chunked_values
    — the reference's 64 KB variable values, value.h:73)."""
    from opendht_tpu.models.chunked_values import (
        announce_chunked, get_chunked,
    )
    from opendht_tpu.models.storage import empty_store

    p, parts, w = args.puts, args.value_parts, args.payload_words
    pls = jax.random.bits(jax.random.PRNGKey(8), (p, parts, w),
                          jnp.uint32)
    lens = (jax.random.randint(jax.random.PRNGKey(9), (p,), 1,
                               parts * w * 4 + 1).astype(jnp.uint32))

    def roundtrip(seed):
        store = empty_store(cfg.n_nodes, scfg)
        store, rep = announce_chunked(swarm, cfg, store, scfg, keys,
                                      vals, seqs, 0,
                                      jax.random.PRNGKey(seed), pls,
                                      lens)
        res = get_chunked(swarm, cfg, store, scfg, keys,
                          jax.random.PRNGKey(seed + 1), parts)
        return rep, res

    def sync(res):
        return int(np.asarray(jnp.sum(res.val[:8])))

    rep, res = roundtrip(2)
    sync(res)
    times = []
    for r in range(args.repeat):
        t0 = time.perf_counter()
        rep, res = roundtrip(10 + 2 * r)
        sync(res)
        times.append(time.perf_counter() - t0)
    dt = min(times)

    hit = np.asarray(res.hit)
    nw = -(-np.asarray(lens).astype(int) // 4)
    got = np.asarray(res.payload)
    want = np.asarray(pls).reshape(p, parts * w)
    mask = np.arange(parts * w)[None, :] < nw[:, None]
    intact = bool(((got == want) | ~mask)[hit].all())
    out = {
        "metric": "swarm_chunked_putget_roundtrips_per_sec",
        "value": round(p / dt, 1),
        "unit": "put+get/s",
        "vs_baseline": round(p / dt / REFERENCE_LOOKUPS_PER_SEC, 2),
        "n_nodes": args.nodes,
        "n_puts": p,
        "slots": scfg.slots,
        "value_parts": parts,
        "max_value_bytes": parts * w * 4,
        "wall_s": round(dt, 4),
        "hit_rate": float(hit.mean()),
        "mean_replicas": float(np.asarray(rep.replicas).mean()),
        "lengths_intact": bool(
            (np.asarray(res.length)[hit]
             == np.asarray(lens)[hit]).all()),
        "payloads_intact": bool(intact),
        "sim_fidelity": "variable-size-values",
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


def churn_main(args):
    """Persistence under mass node death: announce, kill a fraction,
    let survivors republish, re-get — the device twin of the host
    PersistenceTest scenarios (ref python/tools/dht/tests.py:439-827;
    maintenance op: Dht::dataPersistence, src/dht.cpp:2887-2947).

    Reports the survival rate (hit rate after churn + republish) and
    the republish cost; the host-path baseline is 7/8 values re-found
    after killing all hosting nodes (BASELINE.md, persistence delete).
    """
    from opendht_tpu.models.storage import (
        StoreConfig, announce, empty_store, get_values, republish_from,
    )
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    scfg = StoreConfig(slots=auto_slots(args, cfg), listen_slots=4,
                       max_listeners=1 << 10)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])
    p = args.puts
    keys = jax.random.bits(jax.random.PRNGKey(1), (p, 5), jnp.uint32)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    seqs = jnp.ones((p,), jnp.uint32)

    store = empty_store(cfg.n_nodes, scfg)
    store, rep = announce(swarm, cfg, store, scfg, keys, vals, seqs, 0,
                          jax.random.PRNGKey(2))
    pre_replicas = float(np.asarray(rep.replicas).mean())

    # Get workload: uniform (each key once) or Zipf-skewed popularity
    # (the scenario of BASELINE.md "100k-node swarm, Zipf keys, churn").
    if args.zipf > 0:
        rnk = np.arange(1, p + 1, dtype=np.float64)
        prob = rnk ** -args.zipf
        prob /= prob.sum()
        g_idx = np.random.default_rng(9).choice(p, size=p, p=prob)
        get_keys = keys[jnp.asarray(g_idx)]
    else:
        get_keys = keys

    # Churn-detection instrumentation (ISSUE 8 satellite): the kills
    # below run through the SAME freshness plane as --mode monitor
    # (models.monitor.MonitorEngine wrapping the identical churn()
    # call, same keys — survival numbers are unchanged), so this mode
    # reports detection lag from the same code path and the two modes
    # cannot drift apart.  period=1 / miss_limit=1: one full-grid
    # sweep per cycle on the UNHEALED post-kill tables (churn mode
    # never heals — that is its scenario), detection expected by the
    # next sweep (bound = 1).
    from opendht_tpu.models.monitor import MonitorConfig, MonitorEngine

    mon = MonitorEngine(swarm, cfg,
                        MonitorConfig.for_nodes(cfg.n_nodes, period=1,
                                                miss_limit=1))
    mon.sweep(jax.random.PRNGKey(400))       # tracked baseline crawl

    # Repeated kill/republish cycles — one cycle is the delete
    # scenario, several are mult_time (continuous churn with
    # maintenance racing it, ref tests.py:439-827).  Each cycle kills
    # kill_frac of the REMAINING nodes, then survivors republish.
    repub_s = 0.0
    survival_no_repub = None
    all_idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    for r in range(args.rounds):
        mon.kill(args.kill_frac, jax.random.PRNGKey(3 + 10 * r))
        dead = mon.swarm
        if survival_no_repub is None:
            rd = get_values(dead, cfg, store, scfg, get_keys,
                            jax.random.PRNGKey(4))
            survival_no_repub = float(np.asarray(rd.hit).mean())
        t0 = time.perf_counter()
        # Seed schedule disjoint from the churn (3+10r) and the
        # measurement gets (4, 6): maintenance lookups must not share
        # random bits with the survival measurement.
        store, rrep = republish_from(dead, cfg, store, scfg, all_idx,
                                     1 + r, jax.random.PRNGKey(7 + 10 * r))
        _ = int(np.asarray(jnp.sum(rrep.replicas[:8])))
        repub_s += time.perf_counter() - t0
        mon.sweep(jax.random.PRNGKey(400 + 10 * (r + 1)))

    res = get_values(dead, cfg, store, scfg, get_keys,
                     jax.random.PRNGKey(6))
    survival = float(np.asarray(res.hit).mean())
    get_vals = vals if args.zipf <= 0 else vals[jnp.asarray(g_idx)]
    ok_vals = np.asarray(jnp.where(res.hit, res.val == get_vals, True))

    out = {
        "metric": "swarm_churn_survival_rate",
        "value": round(survival, 4),
        "unit": "fraction",
        # Host-path persistence scenario re-found 7/8 after killing all
        # hosting nodes (BASELINE.md).
        "vs_baseline": round(survival / (7 / 8), 3),
        "n_nodes": cfg.n_nodes,
        "n_puts": p,
        "slots": scfg.slots,
        "kill_frac": args.kill_frac,
        "zipf": args.zipf,
        "rounds": args.rounds,
        "alive_frac_final": float(np.asarray(dead.alive).mean()),
        "mean_replicas_before": round(pre_replicas, 2),
        "survival_before_republish": round(survival_no_repub, 4),
        "republish_wall_s": round(repub_s, 3),
        "values_intact": bool(ok_vals.all()),
        # Freshness-plane view of the same kills (the monitor-mode
        # code path — see the MonitorEngine block above): how fast the
        # swarm's own monitoring would have NOTICED this churn.
        "detection_lag_mean": (round(
            sum(r["lag_sum"] for r in mon.records)
            / max(1, sum(r["lag_count"] for r in mon.records)), 3)
            if any(r["lag_count"] for r in mon.records) else None),
        "detection_lag_max": max(
            (r["lag_max"] for r in mon.records if r["lag_count"]),
            default=None),
        "detection_lag_bound_sweeps": mon.mcfg.detection_lag_bound,
        "deaths_detected": sum(r["lag_count"] for r in mon.records),
        "monitor_coverage": mon.records[-1]["coverage"],
        "monitor_false_dead": mon.records[-1]["false_dead"],
        # See putget_main: device values are uint32 tokens, not bytes.
        "sim_fidelity": "token-values",
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


def crawl_main(args):
    """Full-swarm crawl + signed-value verify throughput — the device
    twin of dhtscanner (ref tools/dhtscanner.cpp:43-67: recursive gets
    splitting the keyspace until bucket depth) plus the crawl's value
    signature checking.

    The crawl issues lookups on an evenly spaced keyspace grid ~2x
    oversampled vs the node count; every answered lookup contributes
    its quorum-closest discovered nodes.  Reported: coverage (fraction
    of alive nodes discovered), crawl wall, nodes/s, and host-side
    RSA signed-value verifies/s (the reference's scanner checks values
    as it walks).
    """
    import math as _math

    from opendht_tpu.models.swarm import SwarmConfig, build_swarm, lookup

    n = args.nodes
    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    cfg = SwarmConfig.for_nodes(n, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])

    d = max(1, int(_math.ceil(_math.log2(max(16, n // 4)))))
    g = 1 << d
    j = jnp.arange(g, dtype=jnp.uint32)
    grid = jnp.stack([
        j << jnp.uint32(32 - d),
        *[jnp.full((g,), jnp.uint32(0x80000000)) for _ in range(4)],
    ], axis=1)                                             # [G,5]
    lb = args.lookup_batch or g
    chunks = [grid[lo:lo + lb] for lo in range(0, g, lb)]

    def crawl_once(seed):
        rs = [lookup(swarm, cfg, c, jax.random.PRNGKey(seed + i))
              for i, c in enumerate(chunks)]
        for r in rs:
            _ = int(np.asarray(jnp.sum(r.found[:8])))
        return rs

    crawl_once(1)  # warmup
    t0 = time.perf_counter()
    rs = crawl_once(100)
    dt = time.perf_counter() - t0
    found = np.concatenate([np.asarray(r.found) for r in rs])
    uniq = np.unique(found[found >= 0])
    coverage = len(uniq) / n

    # Signed-value verify throughput (host crypto path).  The
    # ``cryptography`` dep is OPTIONAL (the package imports without
    # it, PR 1); a crawl on a container without it reports the verify
    # rate as null instead of crashing the whole mode.
    vps = None
    try:
        from opendht_tpu.core.value import Value
        from opendht_tpu.crypto.identity import generate_identity
        from opendht_tpu.crypto.securedht import (
            check_value_signature, sign_value)
    except ImportError:
        pass
    else:
        ident = generate_identity("crawler", key_length=2048)
        v = Value(b"x" * 64, value_id=1)
        sign_value(ident.key, v)
        reps = 500
        t1 = time.perf_counter()
        okc = sum(check_value_signature(v) for _ in range(reps))
        vps = reps / (time.perf_counter() - t1)
        assert okc == reps

    out = {
        "metric": "swarm_crawl_coverage",
        "value": round(coverage, 4),
        "unit": "fraction",
        # No vs_baseline: there is no measured host-path crawl coverage
        # to divide by (a self-ratio would misread as parity across
        # modes); the absolute fraction IS the result — and check_bench
        # floors it at 0.99x the recorded BENCH_GATE_r08.json row.
        "n_nodes": n,
        "grid_lookups": g,
        "crawl_wall_s": round(dt, 3),
        "nodes_per_sec": round(len(uniq) / dt, 1),
        "verifies_per_sec_rsa2048": (round(vps, 1) if vps is not None
                                     else None),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


def sharded_main(args):
    """Sharded-path overhead measured on REAL hardware.

    Runs the routed engine (shard_map + all_to_all query routing,
    opendht_tpu.parallel.sharded) on a mesh of all local devices — ONE
    device on the dev chip, so the all_to_all is a self-exchange and
    the measured gap vs the local path is pure sharded-machinery
    overhead (shard_map tracing, routing-bucket construction, the
    collectives themselves).  This converts the v5e-8 "<1 s" north-star
    arithmetic from assumption into measurement: projected wall =
    measured sharded per-lookup cost / n_chips (+ ICI transfer time,
    which a self-exchange bounds below).
    """
    from opendht_tpu.models.storage import (
        StoreConfig, announce, empty_store, get_values,
    )
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm, lookup
    from opendht_tpu.parallel import make_mesh
    from opendht_tpu.parallel.sharded import sharded_lookup
    from opendht_tpu.parallel.sharded_storage import (
        sharded_announce, sharded_empty_store, sharded_get,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])
    l = args.lookups
    targets = jax.random.bits(jax.random.PRNGKey(1), (l, 5), jnp.uint32)
    # Big-table swarms: per-round respond temps scale with the lookup
    # chunk (the [Q, row_w] fetched-rows buffer alone is ~4 GB at
    # L=1M, 10M nodes) — chunk like the local lookups mode, keeping
    # every chunk divisible by the mesh (shard_map's P(AXIS) axis).
    if not args.lookup_batch and args.nodes >= 4_000_000:
        args.lookup_batch = even_chunk_size(l, 262_144, multiple=n_dev)
    lb = args.lookup_batch or l
    t_chunks = [targets[lo:lo + lb] for lo in range(0, l, lb)]

    def timed(fn, sync):
        sync(fn(2))  # warmup/compile — synced, or its execution tail
                     # would bleed into the first timed repeat
        ts = []
        for r in range(args.repeat):
            t0 = time.perf_counter()
            sync(fn(300 + 100 * r))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    def chunked(fn_one):
        def run(seed):
            rs = [fn_one(c, seed + i) for i, c in enumerate(t_chunks)]
            # Sync every chunk (cheap scalar) so none is left in flight.
            for r in rs:
                int(np.asarray(jnp.sum(r.found[:, 0])))
            return LookupResultConcat(rs)
        return run

    # --compact steers both engines: the local reference and (via the
    # burst formulation's ladder) the routed one.  "auto" keeps each
    # engine's own dispatcher default.
    kw_l = {} if args.compact == "auto" else {
        "compact": args.compact == "on"}
    sync_l = lambda r: int(np.asarray(jnp.sum(r.found[:, 0])))
    t_local = timed(chunked(
        lambda c, s: lookup(swarm, cfg, c, jax.random.PRNGKey(s),
                            **kw_l)), sync_l)
    t_shard = timed(chunked(
        lambda c, s: sharded_lookup(swarm, cfg, c,
                                    jax.random.PRNGKey(s), mesh,
                                    capacity_factor=2.0, **kw_l)),
        sync_l)
    ladder = {}
    if args.decompose and n_dev == 1:
        # Overhead ladder on the 1-device mesh: each rung adds one
        # piece of the sharded machinery (BASELINE.md round-5 ask).
        t_struct = timed(chunked(
            lambda c, s: sharded_lookup(swarm, cfg, c,
                                        jax.random.PRNGKey(s), mesh,
                                        local_respond=True)), sync_l)
        t_inf = timed(chunked(
            lambda c, s: sharded_lookup(swarm, cfg, c,
                                        jax.random.PRNGKey(s), mesh,
                                        capacity_factor=float("inf"))),
            sync_l)
        ladder = {
            "local_burst_s": round(t_local, 4),
            "shardmap_whileloop_s": round(t_struct, 4),
            "routed_uncapped_s": round(t_inf, 4),
            "routed_cf2_s": round(t_shard, 4),
            "structure_overhead_frac": round(t_struct / t_local - 1, 4),
            "routing_overhead_frac": round(t_inf / t_struct - 1, 4),
            "capacity_overhead_frac": round(t_shard / t_inf - 1, 4),
        }

    res = chunked(
        lambda c, s: sharded_lookup(swarm, cfg, c, jax.random.PRNGKey(s),
                                    mesh, capacity_factor=2.0))(7)

    # Cost ledger: one instrumented routed replay (kernel walls, cost
    # analysis, HBM watermarks) + the LOCAL round's sub-phase table —
    # the routed engine reuses step_impl's round core, so the local
    # decomposition prices the shared phases; the independently timed
    # lookup_step is the sum cross-check target (no burst p50 here).
    ledger = None
    if args.ledger_out:
        from opendht_tpu.obs.ledger import (CostLedger,
                                            measure_round_phases)
        ledger = CostLedger()
        with ledger.instrument(barrier=True):
            chunked(lambda c, s: sharded_lookup(
                swarm, cfg, c, jax.random.PRNGKey(s), mesh,
                capacity_factor=2.0))(300 + 100 * (args.repeat - 1))
        ledger.sample_hbm()
        ledger.round_phases = measure_round_phases(
            swarm, cfg, t_chunks[0], jax.random.PRNGKey(77),
            repeats=max(2, args.repeat))

    out = {
        "metric": "swarm_sharded_lookups_per_sec",
        "value": round(l / t_shard, 1),
        "unit": "lookups/s",
        "vs_baseline": round(l / t_shard / REFERENCE_LOOKUPS_PER_SEC, 2),
        "n_devices": n_dev,
        "n_nodes": args.nodes,
        "n_lookups": l,
        "wall_s": round(t_shard, 4),
        "local_wall_s": round(t_local, 4),
        "lookup_overhead_frac": round(t_shard / t_local - 1, 4),
        "done_frac": float(np.asarray(res.done).mean()),
        "median_hops": float(np.median(np.asarray(res.hops))),
        "capacity_factor": 2.0,
        "lookup_batch": lb,
        "platform": jax.devices()[0].platform,
    }
    if ladder:
        out["decomposition"] = ladder

    def write_ledger():
        if ledger is not None:
            with open(args.ledger_out, "w") as f:
                json.dump(ledger.to_dict(bench_row=out), f)
                f.write("\n")

    # Storage round-trip: local vs routed announce+get (skipped with
    # --puts 0 — at 10M nodes the side-by-side stores next to the
    # ~10 GB table fragment HBM; measure storage in its own process).
    p = args.puts
    if p == 0:
        write_ledger()
        print(json.dumps(out))
        return
    scfg = StoreConfig(slots=auto_slots(args, cfg), listen_slots=4,
                       max_listeners=1 << 10)
    keys = jax.random.bits(jax.random.PRNGKey(4), (p, 5), jnp.uint32)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    seqs = jnp.ones((p,), jnp.uint32)
    sync_g = lambda r: int(np.asarray(jnp.sum(r.val[:8])))

    def local_putget(s):
        store = empty_store(cfg.n_nodes, scfg)
        store, _ = announce(swarm, cfg, store, scfg, keys, vals, seqs,
                            0, jax.random.PRNGKey(s))
        return get_values(swarm, cfg, store, scfg, keys,
                          jax.random.PRNGKey(s + 1))

    def shard_putget(s):
        store = sharded_empty_store(cfg.n_nodes, scfg, mesh)
        store, _ = sharded_announce(swarm, cfg, store, scfg, keys, vals,
                                    seqs, 0, jax.random.PRNGKey(s),
                                    mesh, capacity_factor=2.0)
        return sharded_get(swarm, cfg, store, scfg, keys,
                           jax.random.PRNGKey(s + 1), mesh,
                           capacity_factor=2.0)

    t_pg_local = timed(local_putget, sync_g)
    t_pg_shard = timed(shard_putget, sync_g)
    out["putget_wall_s"] = round(t_pg_shard, 4)
    out["putget_local_wall_s"] = round(t_pg_local, 4)
    out["putget_overhead_frac"] = round(t_pg_shard / t_pg_local - 1, 4)
    out["slots"] = scfg.slots
    write_ledger()
    print(json.dumps(out))


def repub_main(args):
    """Announce-with-probe vs full-payload republish on the routed
    sharded path: wire traffic at equal survival.

    The reference's two-phase announce probes ``SELECT id,seq`` and
    ships the full value only where missing/stale, refreshing
    otherwise (/root/reference/src/dht.cpp:1237-1339, :1299-1307) —
    the biggest win on maintenance, where most replicas already hold
    the value.  This mode measures exactly that: churn → one republish
    sweep (full vs probed), then a steady-state sweep (no churn —
    every replica fresh), comparing the storage-exchange all_to_all
    words (static accounting, ``storage_wire_words``; the lookup
    phase is identical in both variants) and the post-sweep survival.
    """
    from opendht_tpu.models.storage import StoreConfig
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm, churn
    from opendht_tpu.parallel import make_mesh
    from opendht_tpu.parallel.sharded_storage import (
        sharded_announce, sharded_empty_store, sharded_get,
        sharded_republish, storage_wire_words,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    cfg = SwarmConfig.for_nodes(args.nodes)
    w = args.payload_words or 64     # 256-byte values: maintenance is
    #                                  payload-dominated, as upstream
    # Slot count bounds the maintenance batch (every node × every slot
    # becomes a lookup) — small fixed default, not the HBM-driven auto.
    scfg = StoreConfig(slots=args.slots or 4, listen_slots=4,
                       max_listeners=1 << 10, payload_words=w)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])
    # Puts bounded well under store capacity (n·slots): a ring-evicting
    # overfull store measures eviction, not maintenance.
    p = min(args.puts, cfg.n_nodes * scfg.slots // 16)
    keys = jax.random.bits(jax.random.PRNGKey(1), (p, 5), jnp.uint32)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    seqs = jnp.ones((p,), jnp.uint32)
    payloads = jax.random.bits(jax.random.PRNGKey(8), (p, w), jnp.uint32)
    cf = 4.0
    kf = args.kill_frac
    # Full-value phase provisioning under probe: sized to the expected
    # churn-displaced fraction (+ headroom), not the full announce
    # load.  Kept BELOW 1.0 — per-shard capacity clamps at the actual
    # request count, so on a 1-device mesh any factor ≥ 1 ships
    # identical buckets and the probe saving would read as zero.
    fcf_churn = min(cf, 2 * kf + 0.2)
    fcf_steady = 0.5

    def run_cycles(probe, seed):
        store = sharded_empty_store(cfg.n_nodes, scfg, mesh)
        store, _ = sharded_announce(swarm, cfg, store, scfg, keys, vals,
                                    seqs, 0, jax.random.PRNGKey(seed),
                                    mesh, capacity_factor=cf,
                                    payloads=payloads)
        dead = churn(swarm, jax.random.PRNGKey(100), kf, cfg)
        fcf = fcf_churn if probe else None
        t0 = time.perf_counter()
        store, rep = sharded_republish(dead, cfg, store, scfg, 1,
                                       jax.random.PRNGKey(seed + 2),
                                       mesh, capacity_factor=cf,
                                       probe=probe,
                                       full_capacity_factor=fcf)
        _ = int(np.asarray(jnp.sum(rep.replicas[:8])))
        churn_s = time.perf_counter() - t0
        # Steady-state sweep: nothing changed since the last one, so a
        # probed sweep is almost pure refresh traffic.
        fcf2 = fcf_steady if probe else None
        store, rep2 = sharded_republish(dead, cfg, store, scfg, 2,
                                        jax.random.PRNGKey(seed + 3),
                                        mesh, capacity_factor=cf,
                                        probe=probe,
                                        full_capacity_factor=fcf2)
        _ = int(np.asarray(jnp.sum(rep2.replicas[:8])))
        res = sharded_get(dead, cfg, store, scfg, keys,
                          jax.random.PRNGKey(seed + 4), mesh,
                          capacity_factor=cf)
        surv = float(np.asarray(res.hit).mean())
        hitm = np.asarray(res.hit)
        intact = bool((np.asarray(res.payload)[hitm]
                       == np.asarray(payloads)[hitm]).all())
        p_shard = (cfg.n_nodes // n_dev) * scfg.slots
        words_churn = storage_wire_words(cfg, scfg, p_shard, n_dev, cf,
                                         probe=probe,
                                         full_capacity_factor=fcf)
        words_steady = storage_wire_words(cfg, scfg, p_shard, n_dev, cf,
                                          probe=probe,
                                          full_capacity_factor=fcf2)
        return surv, intact, words_churn, words_steady, churn_s

    s_full, ok_full, w_full, ws_full, t_full = run_cycles(False, 20)
    s_probe, ok_probe, w_probe, ws_probe, t_probe = run_cycles(True, 30)

    out = {
        "metric": "repub_probe_wire_words_reduction",
        "value": round(1 - w_probe / w_full, 4),
        "unit": "fraction",
        "vs_baseline": round(s_probe / max(s_full, 1e-9), 4),
        "baseline_note": "vs_baseline = survival ratio probed/full "
                         "(1.0 = equal survival at the reduced wire "
                         "budget)",
        "n_nodes": cfg.n_nodes,
        "n_puts": p,
        "slots": scfg.slots,
        "payload_bytes": 4 * w,
        "kill_frac": kf,
        "capacity_factor": cf,
        "full_capacity_factor_churn": fcf_churn,
        "full_capacity_factor_steady": fcf_steady,
        "survival_full": round(s_full, 4),
        "survival_probe": round(s_probe, 4),
        "payloads_intact": bool(ok_full and ok_probe),
        "wire_words_churn_full": w_full,
        "wire_words_churn_probe": w_probe,
        "wire_words_steady_full": ws_full,
        "wire_words_steady_probe": ws_probe,
        "steady_reduction": round(1 - ws_probe / ws_full, 4),
        "republish_wall_s_full": round(t_full, 3),
        "republish_wall_s_probe": round(t_probe, 3),
        # The probe phase costs a flat 10 words/slot (incl. the payload
        # digest); it pays off iff the full-phase shrink saves more:
        # (cf−fcf)·(11+W) > cf·10.  At small payloads the reduction is
        # legitimately NEGATIVE — that is the measured break-even, not
        # a regression.  None = fcf saturated to cf (heavy churn):
        # probing never pays.
        "probe_breakeven_payload_words": (
            max(0, math.ceil(10 * cf / (cf - fcf_churn)) - 11)
            if cf > fcf_churn else None),
        "sim_fidelity": "payload-chunks",
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


def repub_profile_main(args):
    """Price ONE republish sweep end-to-end — the artifact ROADMAP #1
    demands: where do the 330–394 s at 65k nodes actually go?

    One sweep re-announces every (node, slot) of the store: an
    ``N·slots``-row batch whose cost splits into the store-row
    EXTRACTION gathers, the per-value LOOKUP phase (the compacted
    burst engine finding each key's quorum-closest — empty slots pay
    it too, masked only at insert), the STORE-INSERT scatter program,
    and HOST ORCHESTRATION (the dispatch gaps between them).  Four
    sweeps, same rng throughout so every replay runs the warm sweep's
    exact compiled programs: warm (compile; also heals the kill),
    TIMED (unbarriered — the honest wall), attribution (barriered
    phase split, ``republish_from(stats=time_phases)``), and an
    instrumented kernel pass (per-kernel walls + cost analysis + HBM
    for the ledger).  The phase rows must reproduce the timed wall
    within ±10 % — gated by ``tools/check_trace.py`` on the
    ``--ledger-out`` artifact and priced by ``tools/roofline.py``.
    """
    from opendht_tpu.models.storage import (
        StoreConfig, announce, empty_store, republish_from,
    )
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm, churn
    from opendht_tpu.obs.ledger import CostLedger

    cfg = SwarmConfig.for_nodes(args.nodes)
    w = args.payload_words or 16
    scfg = StoreConfig(slots=args.slots or 4, listen_slots=4,
                       max_listeners=1 << 10, payload_words=w)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])
    # Live values bounded under store capacity (the repub mode's rule:
    # an overfull ring store would measure eviction, not maintenance).
    p = max(1, min(args.puts, cfg.n_nodes * scfg.slots // 16))
    keys = jax.random.bits(jax.random.PRNGKey(1), (p, 5), jnp.uint32)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    seqs = jnp.ones((p,), jnp.uint32)
    payloads = jax.random.bits(jax.random.PRNGKey(8), (p, w),
                               jnp.uint32)

    store = empty_store(cfg.n_nodes, scfg)
    store, _rep = announce(swarm, cfg, store, scfg, keys, vals, seqs,
                           0, jax.random.PRNGKey(2), payloads=payloads)
    dead = churn(swarm, jax.random.PRNGKey(3), args.kill_frac, cfg)
    all_idx = jnp.arange(cfg.n_nodes, dtype=jnp.int32)
    rng = jax.random.PRNGKey(6)

    def sync(rep):
        return int(np.asarray(jnp.sum(rep.replicas[:8])))

    # Sweep 1: warm/compile (and the post-kill replication heal).
    store, r1 = republish_from(dead, cfg, store, scfg, all_idx, 1, rng)
    sync(r1)
    # TIMED steady-state sweeps, unbarriered, best-of --repeat (the
    # same steady-state convention as every other mode: each replay
    # re-runs the warm sweep's exact programs, so the min is the
    # honest wall the attribution rows must reproduce).
    times = []
    for i in range(args.repeat):
        t0 = time.perf_counter()
        store, r2 = republish_from(dead, cfg, store, scfg, all_idx,
                                   2 + i, rng)
        sync(r2)
        times.append(time.perf_counter() - t0)
    sweep_wall = min(times)
    # Barriered attribution replay (phase split only — the ledger's
    # call barriers must not pollute the phase gaps).
    pstats = {"time_phases": True}
    store, r3 = republish_from(dead, cfg, store, scfg, all_idx,
                               2 + args.repeat, rng, stats=pstats)
    sync(r3)
    # Instrumented kernel pass for the ledger's kernel plane.
    ledger = CostLedger()
    with ledger.instrument(barrier=True):
        store, r4 = republish_from(dead, cfg, store, scfg, all_idx,
                                   3 + args.repeat, rng)
        sync(r4)
    ledger.sample_hbm()

    # Host orchestration = the part of the TIMED (unbarriered) sweep
    # the barriered device phases don't account for — dispatch gaps,
    # host-side batch assembly, the readback.  Computed against the
    # timed wall, NOT the attribution pass's own total (extract +
    # lookup + insert tile that interval exactly, so a within-pass
    # residual would be an algebraic zero, never a measurement).
    parts = (pstats["extract_s"] + pstats["lookup_s"]
             + pstats["insert_s"])
    host_s = max(0.0, sweep_wall - parts)
    rows = [
        {"phase": "value-extract",
         "wall_s": round(pstats["extract_s"], 6)},
        {"phase": "lookup", "wall_s": round(pstats["lookup_s"], 6)},
        {"phase": "store-insert",
         "wall_s": round(pstats["insert_s"], 6)},
        {"phase": "host-orchestration", "wall_s": round(host_s, 6)},
    ]
    batch_rows = int(cfg.n_nodes) * scfg.slots
    ledger.repub_profile = {
        "rows": rows,
        "sweep_wall_s": round(sweep_wall, 6),
        "attr_sweep_wall_s": round(pstats["sweep_total_s"], 6),
        "batch_rows": batch_rows,
        # Post-compaction lookup width (the PR-6 finding's fix: live
        # rows gather into a dense prefix BEFORE the lookup phase, so
        # lookup_rows ≈ next_pow2(live values · replicas) instead of
        # the full N·slots batch).
        "lookup_rows": pstats.get("lookup_rows", batch_rows),
        "live_values": p,
    }

    out = {
        "metric": "swarm_repub_sweep_wall_s",
        "value": round(sweep_wall, 4),
        "unit": "s",
        # No measured host-path republish wall exists to divide by;
        # the phase rows themselves are the deliverable.
        "vs_baseline": None,
        "baseline_note": "repub-profile prices one steady-state "
                         "republish sweep; see repub_phase rows / the "
                         "--ledger-out artifact",
        "n_nodes": cfg.n_nodes,
        "n_values": p,
        "slots": scfg.slots,
        "payload_bytes": 4 * w,
        "kill_frac": args.kill_frac,
        "batch_rows": batch_rows,
        "wall_p50": round(float(np.percentile(times, 50)), 4),
        "wall_p95": round(float(np.percentile(times, 95)), 4),
        "values_per_sec": round(p / sweep_wall, 1),
        "batch_rows_per_sec": round(batch_rows / sweep_wall, 1),
        "mean_replicas_per_value": round(
            float(np.asarray(jnp.sum(r2.replicas))) / p, 2),
        "repub_phase": {r["phase"]: r["wall_s"] for r in rows},
        "store_trace": (r2.trace.to_dict()
                        if r2.trace is not None else None),
        "sim_fidelity": "payload-chunks",
        "platform": jax.devices()[0].platform,
    }
    if args.ledger_out:
        with open(args.ledger_out, "w") as f:
            json.dump(ledger.to_dict(bench_row=out), f)
            f.write("\n")
    print(json.dumps(out))


def chaos_main(args):
    """Chaos-survival: the storage/pub-sub path under COMBINED fault
    injection — mass node death injected MID-maintenance, a fraction
    of every announce/probe exchange dropped, and the full listener
    lifecycle (TTL'd registrations, acks between changes, cancels)
    running through it.  The storage twin of the lookup path's churn
    bench: Kademlia's whole point is serving through massive failure
    (arXiv:1309.5866), and this leg is the measurement that the
    storage half degrades gracefully rather than corrupting.

    One JSON row: survival (primary), value/payload integrity, and a
    listener-continuity block — first delivery, post-chaos redelivery,
    a SECOND value change observed after an ack, and the canceled-
    listener leak rate (must be 0).
    """
    from opendht_tpu.models.storage import StoreConfig
    from opendht_tpu.models.swarm import (
        SwarmConfig, build_swarm, churn, heal_swarm,
    )
    from opendht_tpu.parallel import make_mesh
    from opendht_tpu.parallel.sharded_storage import (
        sharded_ack_listeners, sharded_announce, sharded_cancel_listen,
        sharded_empty_store, sharded_get, sharded_listen_at,
        sharded_republish,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    cfg = SwarmConfig.for_nodes(args.nodes)
    w = args.payload_words or 8
    scfg = StoreConfig(slots=args.slots or 4, listen_slots=4,
                       max_listeners=1 << 12, payload_words=w,
                       listen_ttl=1_000)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])
    # Mesh-divisible batch sizes, puts bounded under store capacity
    # (an overfull ring store would measure eviction, not survival).
    p = max(n_dev,
            min(args.puts, cfg.n_nodes * scfg.slots // 16)
            // n_dev * n_dev)
    nl = max(n_dev, min(p, 2048) // n_dev * n_dev)   # listener subset
    keys = jax.random.bits(jax.random.PRNGKey(1), (p, 5), jnp.uint32)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    seqs = jnp.ones((p,), jnp.uint32)
    payloads = jax.random.bits(jax.random.PRNGKey(8), (p, w), jnp.uint32)
    cf = 4.0
    kf, drop = args.kill_frac, args.drop_frac
    regs = jnp.arange(nl, dtype=jnp.int32)

    store = sharded_empty_store(cfg.n_nodes, scfg, mesh)
    store, ldone = sharded_listen_at(swarm, cfg, store, scfg, keys[:nl],
                                     regs, jax.random.PRNGKey(2), mesh,
                                     capacity_factor=cf, now=0)
    store, rep = sharded_announce(swarm, cfg, store, scfg, keys, vals,
                                  seqs, 1, jax.random.PRNGKey(3), mesh,
                                  capacity_factor=cf, payloads=payloads)
    pre_replicas = float(np.asarray(rep.replicas).mean())
    first_rate = float(np.asarray(store.notified)[:nl].mean())
    store = sharded_ack_listeners(store, regs)

    # --- the chaos cycle: kill kill_frac MID-republish + exchange loss
    half = cfg.n_nodes // 2 // n_dev * n_dev
    dead = swarm
    t0 = time.perf_counter()
    store, _ = sharded_republish(dead, cfg, store, scfg, 2,
                                 jax.random.PRNGKey(4), mesh,
                                 capacity_factor=cf,
                                 node_range=(0, half), drop_frac=drop,
                                 drop_key=jax.random.PRNGKey(5))
    dead = churn(dead, jax.random.PRNGKey(6), kf, cfg)
    store, _ = sharded_republish(dead, cfg, store, scfg, 3,
                                 jax.random.PRNGKey(7), mesh,
                                 capacity_factor=cf,
                                 node_range=(half, cfg.n_nodes),
                                 drop_frac=drop,
                                 drop_key=jax.random.PRNGKey(9))
    # Bucket maintenance after the mass death (heal_swarm): the
    # survival metric must measure STORAGE degradation, not stale-
    # routing-table lookup starvation — the second half-sweep above
    # deliberately still ran on corpse-laden tables (mid-chaos), the
    # healing sweep and the measurement gets below run on healed ones.
    dead = heal_swarm(dead, cfg, jax.random.PRNGKey(16))
    # Healing sweep by the survivors — the probed maintenance shape
    # (full-value phase provisioned to the churn-displaced fraction),
    # still under exchange loss.
    store, hrep = sharded_republish(dead, cfg, store, scfg, 4,
                                    jax.random.PRNGKey(10), mesh,
                                    capacity_factor=cf, probe=True,
                                    full_capacity_factor=min(
                                        cf, 2 * kf + 0.2),
                                    drop_frac=drop,
                                    drop_key=jax.random.PRNGKey(11))
    _ = int(np.asarray(jnp.sum(hrep.replicas[:8])))
    chaos_s = time.perf_counter() - t0

    res = sharded_get(dead, cfg, store, scfg, keys,
                      jax.random.PRNGKey(12), mesh, capacity_factor=cf)
    hit = np.asarray(res.hit)
    survival = float(hit.mean())
    vals_ok = bool(np.asarray(
        jnp.where(res.hit, res.val == vals, True)).all())
    pl_ok = bool((np.asarray(res.payload)[hit]
                  == np.asarray(payloads)[hit]).all())
    # Maintenance re-announces listened-for keys → post-ack redelivery.
    redeliver_rate = float(np.asarray(store.notified)[:nl].mean())

    # --- listener continuity: a SECOND value change after an ack
    store = sharded_ack_listeners(store, regs)
    vals2 = vals + 1_000_000
    pls2 = jax.random.bits(jax.random.PRNGKey(13), (nl, w), jnp.uint32)
    store, _ = sharded_announce(dead, cfg, store, scfg, keys[:nl],
                                vals2[:nl], seqs[:nl] + 1, 5,
                                jax.random.PRNGKey(14), mesh,
                                capacity_factor=cf, payloads=pls2)
    n2 = np.asarray(store.notified)[:nl]
    second_ok = n2 & (np.asarray(store.nvals)[:nl] == np.asarray(
        vals2[:nl]))
    second_rate = float(second_ok.mean())

    # --- cancel half, third change must NOT leak to canceled ids
    store = sharded_cancel_listen(store, scfg, regs[:nl // 2])
    store = sharded_ack_listeners(store, regs)
    store, _ = sharded_announce(dead, cfg, store, scfg, keys[:nl],
                                vals2[:nl] + 1, seqs[:nl] + 2, 6,
                                jax.random.PRNGKey(15), mesh,
                                capacity_factor=cf)
    n3 = np.asarray(store.notified)[:nl]
    canceled_leak = float(n3[:nl // 2].mean())
    active_third_rate = float(n3[nl // 2:].mean())

    out = {
        "metric": "swarm_chaos_survival_rate",
        "value": round(survival, 4),
        "unit": "fraction",
        # Same baseline as churn mode: the host-path persistence
        # scenario re-found 7/8 after killing all hosting nodes
        # (BASELINE.md).
        "vs_baseline": round(survival / (7 / 8), 3),
        "n_nodes": cfg.n_nodes,
        "n_puts": p,
        "slots": scfg.slots,
        "payload_bytes": 4 * w,
        "kill_frac": kf,
        "drop_frac": drop,
        "mid_republish_kill": True,
        "alive_frac_final": float(np.asarray(dead.alive).mean()),
        "mean_replicas_before": round(pre_replicas, 2),
        "chaos_wall_s": round(chaos_s, 3),
        "values_intact": vals_ok,
        "payloads_intact": pl_ok,
        "listeners": nl,
        "listen_first_delivery_rate": round(first_rate, 4),
        "listen_redelivery_rate": round(redeliver_rate, 4),
        "listen_second_change_rate": round(second_rate, 4),
        "listen_canceled_leak_rate": round(canceled_leak, 4),
        "listen_active_third_rate": round(active_third_rate, 4),
        "sim_fidelity": "payload-chunks",
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


def monitor_main(args):
    """Swarm-health monitoring: continuous incremental crawl under
    churn (ROADMAP #5, the arXiv:1009.3681 monitoring scenario).

    Sweep 0 is a full keyspace crawl; every later sweep first kills
    ``--kill-frac`` of the remaining nodes (plus one contiguous
    ``--outage-frac`` range at mid-run), heals the survivors' routing
    tables, then probes only the STALE keyspace buckets (the
    ``models.monitor`` scheduler: phase-jittered periodic refresh +
    freshness-deficit + pending-confirmation triggers) through the
    compacted burst engine.  The reported number is the steady-state
    COVERAGE (tracked∩alive / alive, averaged over the post-initial
    sweeps) next to the measured churn-detection lag against the
    scheduler's stated bound, the freshness percentiles, the hop-
    histogram-vs-analytic-model fidelity (``obs.health``), and the
    per-bucket keyspace-density profile vs the Poisson random-ID law.
    ``--monitor-out`` dumps the artifact ``tools/check_trace.py``
    gates (freshness conservation, lag ≤ bound, hop band) and
    ``tools/check_bench.py`` floors (coverage ≥ 0.99× recorded).
    """
    from opendht_tpu.models.monitor import MonitorConfig, MonitorEngine
    from opendht_tpu.models.swarm import (
        LookupFaults, SwarmConfig, build_swarm, corrupt_swarm,
    )
    from opendht_tpu.obs.health import (hop_fidelity, SwarmHealthPlane,
                                    summarize_sweeps)
    from opendht_tpu.utils.metrics import MetricsRegistry

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])
    faults = None
    if args.byzantine_frac:
        # Sweeps run the DEFENDED chaos engine: a convicted liar is
        # censored from results, stops being seen, and is eventually
        # presumed departed — the monitor's view of an attacker
        # leaving the honest overlay.
        swarm = corrupt_swarm(swarm, jax.random.PRNGKey(9),
                              args.byzantine_frac, cfg)
        faults = LookupFaults(seed=11)
    mcfg = MonitorConfig.for_nodes(
        args.nodes, period=args.monitor_period,
        fresh_ttl=args.fresh_ttl,
        stale_threshold=args.stale_threshold,
        miss_limit=args.miss_limit)
    engine = MonitorEngine(swarm, cfg, mcfg, faults=faults)
    registry = MetricsRegistry()
    plane = SwarmHealthPlane(registry)
    outage_sweep = max(1, args.sweeps // 2) if args.outage_frac else -1
    for s in range(args.sweeps):
        if s:
            engine.kill(args.kill_frac, jax.random.PRNGKey(100 + s))
            if s == outage_sweep:
                n0 = cfg.n_nodes // 2
                engine.kill_range(
                    n0, n0 + int(cfg.n_nodes * args.outage_frac))
            engine.heal(jax.random.PRNGKey(200 + s))
        t0 = time.perf_counter()
        rec, _res = engine.sweep(jax.random.PRNGKey(300 + s))
        # The fold's stats device_get is the completion barrier; the
        # sweep wall therefore includes lookups + fold + readback.
        rec["wall_s"] = round(time.perf_counter() - t0, 4)
        plane.publish_sweep(rec)

    recs = engine.records
    # ONE sweep-record reduction, shared with the soak bench and the
    # soak checker's recomputation (obs.health.summarize_sweeps) — a
    # second inline copy here would let the two modes' "same" summary
    # fields drift apart.
    summary = summarize_sweeps(recs)
    fidelity = hop_fidelity(engine.hop_hist_initial,
                            engine.initial_alive,
                            bucket_k=cfg.bucket_k, alpha=cfg.alpha,
                            quorum=cfg.quorum)
    density = plane.publish_density(engine.bucket_counts[0])
    walls = [r["wall_s"] for r in recs]
    out = {
        "metric": "swarm_monitor_coverage",
        "value": summary["coverage_mean"],
        "unit": "fraction",
        # No host-path continuous monitor exists to divide by; the
        # one-shot crawl row (BENCH_GATE_r08.json) is the static
        # reference this mode generalizes.
        "vs_baseline": None,
        "baseline_note": "steady-state coverage (mean over post-"
                         "initial sweeps) under continuous churn; "
                         "gated as an absolute floor by check_bench",
        "n_nodes": args.nodes,
        "sweeps": args.sweeps,
        "kill_frac": args.kill_frac,
        "outage_frac": args.outage_frac,
        "byzantine_frac": args.byzantine_frac,
        "grid_depth": mcfg.depth,
        "grid_buckets": engine.n_buckets,
        "period": mcfg.period,
        "fresh_ttl": mcfg.fresh_ttl,
        "miss_limit": mcfg.miss_limit,
        "stale_threshold": mcfg.stale_threshold,
        "detection_lag_bound_sweeps": mcfg.detection_lag_bound,
        "coverage_min": summary["coverage_min"],
        "coverage_final": summary["coverage_final"],
        "detection_lag_mean": summary["detection_lag_mean"],
        "detection_lag_max": summary["detection_lag_max"],
        "deaths_detected": summary["deaths_detected"],
        "false_dead_final": summary["false_dead_final"],
        "false_alive_final": summary["false_alive_final"],
        "freshness_p50_final": summary["freshness_p50_final"],
        "freshness_p99_final": summary["freshness_p99_final"],
        "buckets_probed_mean": round(
            float(np.mean([r["buckets_probed"] for r in recs])), 1),
        "lookups_total": summary["lookups_total"],
        "done_frac": round(
            float(np.mean([r["done_frac"] for r in recs])), 6),
        "sweep_wall_p50": round(float(np.percentile(walls, 50)), 4),
        "sweep_wall_p95": round(float(np.percentile(walls, 95)), 4),
        "hop_tv": fidelity["tv"],
        "hop_median_measured": fidelity["median_measured"],
        "hop_median_model": fidelity["median_model"],
        "hop_band_tv": fidelity["band_tv"],
        "hop_fidelity_ok": fidelity["ok"],
        "density_poisson_tv": density["tv"],
        "platform": jax.devices()[0].platform,
    }
    if args.monitor_out:
        obj = {
            "kind": "swarm_monitor_trace",
            "bench": out,
            "monitor": {
                "config": {
                    "depth": mcfg.depth,
                    "period": mcfg.period,
                    "fresh_ttl": mcfg.fresh_ttl,
                    "stale_threshold": mcfg.stale_threshold,
                    "miss_limit": mcfg.miss_limit,
                    "age_cap": mcfg.age_cap,
                    "detection_lag_bound_sweeps":
                        mcfg.detection_lag_bound,
                    "bucket_k": cfg.bucket_k,
                    "alpha": cfg.alpha,
                    "quorum": cfg.quorum,
                    "max_steps": cfg.max_steps,
                },
                "sweeps": recs,
                "hop_histogram_initial": [
                    int(v) for v in engine.hop_hist_initial],
                "initial_alive": engine.initial_alive,
                "hop_histogram_all_sweeps": [
                    int(v) for v in engine.hop_hist],
                "hop_fidelity": fidelity,
                "density": density,
            },
            "metrics_prometheus": registry.render_prometheus(),
        }
        with open(args.monitor_out, "w") as f:
            json.dump(obj, f)
            f.write("\n")
    print(json.dumps(out))


def index_main(args):
    """Device-native PHT secondary index: build + range-scan workload
    (ROADMAP #5, the read-heavy scan class of arXiv:1009.3681).

    Build: ``--entries`` index entries whose keys are Zipf(``--zipf``)
    draws over ``--key-pool`` ranks (rank → 4-byte big-endian key, so
    hot ranks cluster in linearized key space; per-key multiplicity
    capped at the 16-entry leaf rule) are inserted through
    ``DeviceIndex.insert_batch`` — every probe/put is a batched device
    program over the ``--nodes``-node swarm store.

    Scan: ``--scans`` inclusive rank windows of ``--scan-span`` (hot-
    biased like the inserts) run as ONE batched ``range_query`` per
    pass, closed-loop, best-of ``--repeat``.  Every pass's result is
    held against a sequential in-memory host-PHT oracle replaying the
    same entry list: the bench FAILS unless every range returns
    EXACTLY the oracle's entry set (recall 1.0, no extras).

    ``--index-out`` dumps the ``swarm_index_trace`` artifact: leaf-
    occupancy histogram (≤ 16 everywhere), split accounting
    conservation (leaves == 1 + split levels; entries in leaves +
    overfull drops == distinct entries), probe-round bound compliance,
    and the scan recall — all re-validated by
    ``tools/check_trace.py``.
    """
    import struct

    from opendht_tpu.models.index import (
        DeviceIndex, IndexSpec, PhtOracle,
    )
    from opendht_tpu.models.storage import StoreConfig, empty_store
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm

    spec = IndexSpec.from_key_spec("bench", {"k": 4})
    cfg = SwarmConfig.for_nodes(args.nodes)
    scfg = StoreConfig(slots=max(args.slots, 24), listen_slots=1,
                       max_listeners=64,
                       payload_words=spec.payload_words)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])

    # --- Zipf-keyed entry list (shared verbatim with the oracle).
    u = args.key_pool
    rng = np.random.default_rng(7)
    if args.zipf > 0:
        p = 1.0 / np.arange(1, u + 1, dtype=np.float64) ** args.zipf
        p /= p.sum()
    else:
        p = np.full(u, 1.0 / u)
    draws = rng.choice(u, size=args.entries, p=p)
    per_key: dict = {}
    ranks, dups = [], []
    capped = 0
    for r in draws:
        c = per_key.get(int(r), 0)
        if c >= 16:          # a 17th same-key entry cannot exist in a
            capped += 1      # leaf — the structural cap, counted
            continue
        per_key[int(r)] = c + 1
        ranks.append(int(r))
        dups.append(c)
    k = len(ranks)
    keys = [{"k": struct.pack(">I", r)} for r in ranks]
    ehash = np.stack([np.frombuffer(
        hashlib.sha1(b"e%d.%d" % (r, d)).digest(), dtype=">u4")
        for r, d in zip(ranks, dups)]).astype(np.uint32)
    evid = np.arange(k, dtype=np.uint32)

    # --- build
    ix = DeviceIndex(swarm, cfg, empty_store(cfg.n_nodes, scfg), scfg,
                     spec, seed=3)
    t0 = time.perf_counter()
    ix.insert_batch(keys, ehash, evid)
    build_wall = time.perf_counter() - t0
    build_stats = dict(ix.stats)

    # --- the sequential host-PHT oracle (same rules, same entries)
    orc = PhtOracle(spec)
    bits = ix.linearize(keys)
    for i in range(k):
        orc.insert(bits[i], ehash[i].astype(">u4").tobytes(),
                   int(evid[i]))
    orc_leaves = orc.leaves()

    # --- scan ranges (hot-biased rank windows, inclusive)
    lo_ranks = rng.choice(u, size=args.scans, p=p)
    lo_ranks = np.minimum(lo_ranks, u - 1)
    hi_ranks = np.minimum(lo_ranks + args.scan_span - 1, u - 1)
    lo_bits = ix.linearize(
        [{"k": struct.pack(">I", int(r))} for r in lo_ranks])
    hi_bits = ix.linearize(
        [{"k": struct.pack(">I", int(r))} for r in hi_ranks])
    want = [orc.entries_in_range(lo_bits[i], hi_bits[i])
            for i in range(args.scans)]
    want_total = sum(len(w) for w in want)

    # Warm pass (compiles), then timed best-of --repeat; the warm
    # pass also carries the exactness verdict (every timed pass runs
    # the same deterministic walk).
    res, leaves = ix.range_query(lo_bits, hi_bits)
    matched = sum(len(set(res[i]) & want[i])
                  for i in range(args.scans))
    extras = sum(len(set(res[i]) - want[i]) for i in range(args.scans))
    recall = (matched / want_total) if want_total else 1.0
    exact = extras == 0 and matched == want_total
    walls = []
    scan_stats = None
    for _i in range(max(1, args.repeat)):
        s_before = dict(ix.stats)
        t0 = time.perf_counter()
        res2, _lv = ix.range_query(lo_bits, hi_bits)
        walls.append(time.perf_counter() - t0)
        if scan_stats is None:
            # Per-PASS probe cost (bracketing exactly one timed pass —
            # the walk is deterministic, so every pass costs the same).
            scan_stats = {k2: ix.stats[k2] - s_before[k2]
                          for k2 in ("probe_batches", "probe_keys")}
    scan_wall = min(walls)
    returned = sum(len(r) for r in res)

    # --- trie accounting (read back from the store, not the builder)
    occ_hist = [0] * (17)
    for ents in orc_leaves.values():
        occ_hist[len(ents)] += 1
    dev_leaves, dev_interior = ix.trie_snapshot()
    entries_in_leaves = sum(len(v) for v in dev_leaves.values())
    occ_dev = [0] * 17
    for ents in dev_leaves.values():
        occ_dev[len(ents)] += 1

    out = {
        "metric": "swarm_index_scan_entries_per_sec",
        "value": round(returned / scan_wall, 1) if scan_wall else 0.0,
        "unit": "entries/s",
        # The reference PHT walks one async callback chain per key
        # with no batch surface at all — there is no host rate to
        # divide by; exactness vs the sequential oracle IS the
        # deliverable, the rate is the record.
        "vs_baseline": None,
        "baseline_note": "host Pht is per-key async callbacks; exact "
                         "recall vs its sequential oracle is the "
                         "gate, see --index-out artifact",
        "n_nodes": cfg.n_nodes,
        "entries": k,
        "entries_capped": capped,
        "key_pool": u,
        "zipf": args.zipf,
        "scans": args.scans,
        "scan_span": args.scan_span,
        "build_wall_s": round(build_wall, 4),
        "build_entries_per_sec": round(k / build_wall, 1),
        "scan_wall_s": round(scan_wall, 6),
        "wall_p50": round(float(np.percentile(walls, 50)), 6),
        "wall_p95": round(float(np.percentile(walls, 95)), 6),
        "entries_returned": returned,
        "scan_recall": round(recall, 6),
        "scan_exact": bool(exact),
        "leaves_touched_mean": round(float(leaves.mean()), 2),
        "n_leaves": len(dev_leaves),
        "splits": build_stats["splits"],
        "walk_rounds_max": ix.stats["walk_rounds_max"],
        "probe_round_bound": spec.probe_round_bound,
        "overfull_drops": ix.stats["overfull_drops"],
        "sim_fidelity": "payload-values",
        "platform": jax.devices()[0].platform,
    }
    if args.index_out:
        artifact = {
            "kind": "swarm_index_trace",
            "bench": out,
            "index": {
                "prefix_bits": spec.prefix_bits,
                "probe_round_bound": spec.probe_round_bound,
                "walk_rounds_max": ix.stats["walk_rounds_max"],
                "entries_distinct": k,
                "entries_in_leaves": entries_in_leaves,
                "overfull_drops": ix.stats["overfull_drops"],
                "n_leaves": len(dev_leaves),
                "n_interior": len(dev_interior),
                "splits": build_stats["splits"],
                "split_levels": build_stats["split_levels"],
                "leaf_occupancy_max": max(
                    (len(v) for v in dev_leaves.values()), default=0),
                "leaf_occupancy_hist": occ_dev,
                "oracle_leaf_occupancy_hist": occ_hist,
                "oracle_agrees": occ_dev == occ_hist
                and len(dev_leaves) == len(orc_leaves),
                "build_stats": build_stats,
                "scans": {
                    "n": args.scans,
                    "span_ranks": args.scan_span,
                    "recall": round(recall, 6),
                    "exact": bool(exact),
                    "entries_expected": want_total,
                    "entries_returned": returned,
                    "extras": extras,
                    "leaves_touched_mean": round(
                        float(leaves.mean()), 2),
                    "probe_batches": scan_stats["probe_batches"],
                    "probe_keys": scan_stats["probe_keys"],
                },
            },
        }
        with open(args.index_out, "w") as f:
            json.dump(artifact, f)
            f.write("\n")
    print(json.dumps(out))
    if not exact:
        print(f"bench: index scan NOT exact — matched {matched} / "
              f"{want_total}, {extras} extras", file=sys.stderr)
        return 1
    return 0


def soak_main(args):
    """Always-on node soak: serve + maintenance + monitor in ONE
    engine (ROADMAP #2, the reference's scheduler loop,
    include/opendht/scheduler.h:38-123).

    Setup announces ``--puts`` tracked values (the survival set) and
    registers listeners, then drives a Poisson/Zipf arrival stream
    (``--mix`` read/write/scan fractions) through the slot-recycled
    soak engine while republish sweeps, monitor sweeps and listener
    refreshes interleave as micro-batches into FREE serve slots —
    churn every ``--churn-every`` seconds and one contiguous
    ``--outage-frac`` keyspace outage at mid-run, all DURING serving.
    With ``--interference on`` (default) the SAME schedule then runs a
    maintenance-OFF arm (writes, scans and faults still on — only
    republish/monitor/listener work withheld) and the interference
    ledger attributes the serve-p99 delta to maintenance bursts: the
    measured cost of interleaving the 5.73 s standalone sweep.

    The artifact (``--soak-out``, kind ``swarm_soak_trace``) carries
    the per-interval timeline (slot-round splits, latency histograms,
    lifecycle boundary snapshots), the monitor block (freshness
    conservation + detection lag vs the scheduler bound), the
    republish block (sweep records + value survival on the tracked
    keyset), the SLO gauges, and the interference ledger —
    ``tools/check_trace.py check_soak_obj`` re-derives and gates all
    of it; ``tools/check_bench.py`` floors the rate/p99/coverage/
    survival against the recorded register row.  Overload exits 2
    with the lower-rate-or-raise-slots message.
    """
    import struct

    from opendht_tpu.models.monitor import MonitorConfig, MonitorEngine
    from opendht_tpu.models.serve import ServeOverloadError
    from opendht_tpu.models.soak import (
        ScenarioEvent, SoakConfig, SoakEngine, mixed_events,
        soak_open_loop,
    )
    from opendht_tpu.models.storage import (
        StoreConfig, announce, empty_store, get_values, listen_at,
    )
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm
    from opendht_tpu.obs.health import summarize_sweeps
    from opendht_tpu.obs.latency import LatencyPlane
    from opendht_tpu.obs.timeline import (
        SoakPlane, SoakTimeline, interference_ledger,
    )
    from opendht_tpu.utils.metrics import Histogram, MetricsRegistry

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    store_slots = args.slots or 4
    # Chunked-station values live in the SAME soak store, so mixing
    # chunk ops in (--chunk-frac) arms payload rows store-wide; the
    # token-only store stays the default shape.
    pw = args.payload_words or (2 if args.chunk_frac > 0 else 0)
    scfg = StoreConfig(slots=store_slots, listen_slots=4,
                       max_listeners=1 << 10, payload_words=pw)
    p = min(args.puts, args.nodes * store_slots // 16)
    put_keys = jax.random.bits(jax.random.PRNGKey(11), (p, 5),
                               jnp.uint32)
    zipf_s = 1.1 if args.zipf is None else args.zipf
    ts, keys, klass, ops, scan_lo, scan_hi = mixed_events(
        rate=args.arrival_rate, duration=args.duration,
        key_pool=args.key_pool, zipf_s=zipf_s, seed=7,
        write_frac=args.write_frac, scan_frac=args.scan_frac,
        scan_span=args.scan_span, chunk_frac=args.chunk_frac,
        chunk_write_frac=args.chunk_write_frac)
    mcfg = MonitorConfig.for_nodes(
        args.nodes, period=args.monitor_period,
        fresh_ttl=args.fresh_ttl,
        stale_threshold=args.stale_threshold,
        miss_limit=args.miss_limit)
    soak_cfg = SoakConfig(interval_s=args.soak_interval,
                          repub_period_s=args.repub_period,
                          monitor_gap_s=args.monitor_gap,
                          maint_cap=args.maint_cap,
                          maint_slot_frac=args.maint_slot_frac)
    scenario = []
    if args.churn_every > 0 and args.kill_frac > 0:
        t_ev = args.churn_every
        while t_ev < args.duration:
            scenario.append(ScenarioEvent(t_ev, "churn",
                                          args.kill_frac))
            t_ev += args.churn_every
    if args.outage_frac > 0:
        scenario.append(ScenarioEvent(args.duration / 2, "outage",
                                      args.outage_frac))
    slo_s = args.slo_ms / 1e3
    spec = None
    if args.scan_frac > 0:
        from opendht_tpu.models.index import IndexSpec
        spec = IndexSpec.from_key_spec("bench", {"k": 4})

    def build_arm(with_monitor: bool):
        """One A/B arm from identical seeds: same swarm, same initial
        store content, same index entries — the arms differ ONLY in
        whether maintenance runs."""
        swarm = build_swarm(jax.random.PRNGKey(0), cfg)
        _ = np.asarray(swarm.tables[:1, :1])
        store = empty_store(cfg.n_nodes, scfg)
        store, rep0 = announce(swarm, cfg, store, scfg, put_keys,
                               jnp.arange(p, dtype=jnp.uint32) + 1,
                               jnp.ones((p,), jnp.uint32), 0,
                               jax.random.PRNGKey(12))
        nl = min(64, p)
        store, _regs = listen_at(swarm, cfg, store, scfg,
                                 put_keys[:nl],
                                 jnp.arange(nl, dtype=jnp.int32),
                                 jax.random.PRNGKey(13), 0)
        index = scan_key_fn = None
        if spec is not None:
            from opendht_tpu.models.index import DeviceIndex
            iscfg = StoreConfig(slots=24, listen_slots=1,
                                max_listeners=64,
                                payload_words=spec.payload_words)
            index = DeviceIndex(swarm, cfg,
                                empty_store(cfg.n_nodes, iscfg),
                                iscfg, spec, seed=3)
            n_ent = min(args.entries, 16 * args.key_pool)
            rng = np.random.default_rng(7)
            draws = rng.integers(0, args.key_pool, size=n_ent)
            per_key, ranks, dups = {}, [], []
            for r in draws:
                cnt = per_key.get(int(r), 0)
                if cnt >= 16:
                    continue
                per_key[int(r)] = cnt + 1
                ranks.append(int(r))
                dups.append(cnt)
            ekeys = [{"k": struct.pack(">I", r)} for r in ranks]
            ehash = np.stack([np.frombuffer(
                hashlib.sha1(b"e%d.%d" % (r, d)).digest(),
                dtype=">u4")
                for r, d in zip(ranks, dups)]).astype(np.uint32)
            index.insert_batch(ekeys, ehash,
                               np.arange(len(ranks), dtype=np.uint32))
            scan_key_fn = (lambda rank:
                           {"k": struct.pack(">I", int(rank))})
        mon = MonitorEngine(swarm, cfg, mcfg) if with_monitor else None
        if mon is not None and args.monitor_bootstrap:
            # Bootstrap crawl, closed-loop and OFF the soak clock (the
            # node joining the swarm); the soak's interleaved sweeps
            # then start at the steady-state incremental width.
            mon.sweep(jax.random.PRNGKey(400))
        station = None
        if args.chunk_frac > 0:
            from opendht_tpu.models.serve import ChunkedStation
            station = ChunkedStation(cfg, scfg,
                                     parts=args.chunk_parts,
                                     pool=32, batch=16, seed=5)
        soak = SoakEngine(swarm, cfg, slots=args.serve_slots,
                          scfg=scfg, store=store, monitor=mon,
                          index=index, scan_key_fn=scan_key_fn,
                          soak_cfg=soak_cfg,
                          maint_key=jax.random.PRNGKey(0x50AC),
                          cache_slots=args.serve_cache,
                          chunk_station=station)
        return soak, rep0

    def survival(soak_arm):
        res = get_values(soak_arm.swarm, cfg, soak_arm.store, scfg,
                         put_keys, jax.random.PRNGKey(99))
        return round(float(np.asarray(res.hit).mean()), 6)

    registry = MetricsRegistry()
    plane = LatencyPlane(registry, prefix="dht_soak_request",
                         label_names=("op",), slo_target_s=slo_s)
    soak_plane = SoakPlane(registry)

    def run_arm(maintenance: bool, lat_plane):
        soak, rep0 = build_arm(with_monitor=maintenance)
        tl = SoakTimeline(args.soak_interval, args.serve_slots,
                          slo_target_s=slo_s)
        try:
            rep = soak_open_loop(
                soak, ts, keys, jax.random.PRNGKey(3), klass=klass,
                ops=ops, scan_lo=scan_lo, scan_hi=scan_hi,
                burst=args.serve_burst, duration=args.duration,
                maintenance=maintenance, scenario=tuple(scenario),
                timeline=tl, latency_plane=lat_plane)
        except ServeOverloadError as e:
            print(f"bench: {e}", file=sys.stderr)
            sys.exit(2)
        return soak, tl, rep, rep0

    soak_on, tl_on, rep, _rep0 = run_arm(True, plane)
    survival_on = survival(soak_on)
    mon_summary = summarize_sweeps(soak_on.mon.records) \
        if soak_on.mon is not None and soak_on.mon.records else None
    ledger = None
    survival_off = None
    tl_off = None
    if args.interference == "on":
        soak_off, tl_off, rep_off, _ = run_arm(False, None)
        survival_off = survival(soak_off)
        ledger = interference_ledger(tl_on.to_obj(), tl_off.to_obj())

    for row in tl_on.rows:
        soak_plane.publish_interval(row)

    # Overall slot-served latency distribution = the timeline rows'
    # histogram sum (scan latencies are summarized separately — see
    # obs.timeline's class contract).
    bounds = tl_on.bounds
    counts = np.sum([r["latency_counts"] for r in tl_on.rows],
                    axis=0).astype(int) if tl_on.rows \
        else np.zeros(len(bounds) + 1, int)
    lat_sum = float(sum(r["latency_sum_s"] for r in tl_on.rows))
    agg = Histogram("soak_latency_agg", "", buckets=bounds)
    agg.observe_bulk([int(v) for v in counts], lat_sum)
    n_lat = int(counts.sum())
    quants = {nm: (round(agg.quantile(q), 6) if n_lat else None)
              for nm, q in (("p50", 0.50), ("p95", 0.95),
                            ("p99", 0.99), ("p999", 0.999))}
    slo_violations = sum(r["slo_violations"] for r in tl_on.rows)
    slo_ratio = round(slo_violations / n_lat, 6) if n_lat else 0.0
    offered = rep["admitted"] + rep["never_admitted"]
    lag_max = mon_summary["detection_lag_max"] if mon_summary else None
    cov = mon_summary["coverage_mean"] if mon_summary else None

    out = {
        "metric": "swarm_soak_req_per_sec",
        "value": round(rep["sustained_rps"], 1),
        "unit": "req/s",
        "vs_baseline": round(rep["sustained_rps"] / 1600.0, 2),
        "baseline_note": "vs the reference's 1600 req/s global "
                         "inbound rate cap (include/opendht/"
                         "network_engine.h:462), WITH maintenance + "
                         "monitoring interleaved",
        "n_nodes": args.nodes,
        "arrival_rate": args.arrival_rate,
        "duration_s": args.duration,
        "elapsed_s": round(rep["elapsed_s"], 4),
        "serve_slots": rep["slots"],
        "burst": rep["burst"],
        "rounds": rep["rounds"],
        "mix": args.mix,
        "write_frac": args.write_frac,
        "scan_frac": args.scan_frac,
        "kill_frac": args.kill_frac,
        "churn_every_s": args.churn_every,
        "outage_frac": args.outage_frac,
        "admitted": rep["admitted"],
        "completed": rep["completed"],
        "expired": rep["expired"],
        "in_flight": rep["in_flight"],
        "done_frac": round(rep["completed"] / offered, 6)
        if offered else 0.0,
        "latency_p50_s": quants["p50"],
        "latency_p95_s": quants["p95"],
        "latency_p99_s": quants["p99"],
        "latency_p999_s": quants["p999"],
        "slot_occupancy_frac": round(rep["slot_occupancy_frac"], 4),
        "wclass_mismatches": rep["wclass_mismatches"],
        "slo_target_s": slo_s,
        "slo_violation_ratio": slo_ratio,
        "slo_violation_max": args.slo_violation_max,
        "slo_error_budget_burn_rate": round(plane.burn_rate, 3),
        "repub_sweeps": len(rep["repub_sweeps"]),
        "monitor_sweeps": len(rep["monitor_sweeps"]),
        "maint_ops": len(rep["maint_ops"]),
        "monitor_coverage": cov,
        "detection_lag_max": lag_max,
        "detection_lag_bound_sweeps": mcfg.detection_lag_bound,
        "deaths_detected": mon_summary["deaths_detected"]
        if mon_summary else None,
        "value_survival_initial": 1.0,
        "value_survival_final": survival_on,
        "value_survival_off_arm": survival_off,
        "scan_completed": rep["scan"]["completed"],
        "scan_latency_mean_s": rep["scan"]["latency_mean_s"],
        "chunk_frac": args.chunk_frac,
        "chunk_completed": rep["chunked"]["completed"],
        "chunk_garbled": rep["chunked"]["garbled"],
        "cache_slots": rep["cache_slots"],
        "cache_hits": rep["cache_hits"],
        "cache_misses": rep["cache_misses"],
        "cache_hit_frac": (
            round(rep["cache_hits"]
                  / rep["lifecycle_by_class"]["read"]["admitted"], 4)
            if rep["cache_slots"]
            and rep["lifecycle_by_class"]["read"]["admitted"]
            else None),
        "maint_interference_p99_delta_s": ledger["p99_delta_s"]
        if ledger else None,
        "maint_p99_on_s": ledger["p99_on_s"] if ledger else None,
        "maint_p99_off_s": ledger["p99_off_s"] if ledger else None,
        "zipf_s": zipf_s,
        "key_pool": args.key_pool,
        "puts": p,
        "platform": jax.devices()[0].platform,
    }
    if args.soak_out:
        obj = {
            "kind": "swarm_soak_trace",
            "bench": out,
            "lifecycle": {
                "by_class": rep["lifecycle_by_class"],
                "admitted": rep["admitted"],
                "completed": rep["completed"],
                "expired": rep["expired"],
                "in_flight": rep["in_flight"],
                "never_admitted": rep["never_admitted"],
                "wclass_mismatches": rep["wclass_mismatches"],
                "scan": rep["scan"],
                "chunked": rep["chunked"],
                "cache_slots": rep["cache_slots"],
                "cache_hits": rep["cache_hits"],
                "cache_misses": rep["cache_misses"],
            },
            "timeline": tl_on.to_obj(),
            "timeline_off": tl_off.to_obj()
            if tl_off is not None else None,
            "interference": ledger,
            "monitor": {
                "config": {
                    "depth": mcfg.depth,
                    "period": mcfg.period,
                    "fresh_ttl": mcfg.fresh_ttl,
                    "stale_threshold": mcfg.stale_threshold,
                    "miss_limit": mcfg.miss_limit,
                    "age_cap": mcfg.age_cap,
                    "detection_lag_bound_sweeps":
                        mcfg.detection_lag_bound,
                    "bucket_k": cfg.bucket_k,
                    "alpha": cfg.alpha,
                    "quorum": cfg.quorum,
                    "max_steps": cfg.max_steps,
                },
                "sweeps": soak_on.mon.records
                if soak_on.mon is not None else [],
                "summary": mon_summary,
            },
            "repub": {
                "period_s": args.repub_period,
                "sweeps": rep["repub_sweeps"],
                "survival_initial": 1.0,
                "survival_final": survival_on,
                "survival_off_arm": survival_off,
                # Scenario-derived floor: keys wholly inside a
                # contiguous outage lose every replica at once and no
                # republish can resurrect them (the checker recomputes
                # the minimum admissible floor from outage_frac).
                "survival_floor": round(
                    max(0.9, 1.0 - 1.5 * args.outage_frac - 0.002),
                    6),
                "tracked_values": p,
            },
            "maint_ops": rep["maint_ops"],
            "latency_histogram": {
                "bounds": bounds,
                "counts": [int(v) for v in counts],
                "sum": round(lat_sum, 6),
                "count": n_lat,
            },
            "latency_quantiles_s": quants,
            "metrics_prometheus": registry.render_prometheus(),
        }
        with open(args.soak_out, "w") as f:
            json.dump(obj, f)
            f.write("\n")
    print(json.dumps(out))


def auth_main(args):
    """Device integrity plane: the authenticated-values workload
    (ROADMAP #5 — the last closed workload class).

    Three stages, one JSON row:

    * **overhead A/B** — the same honest announce+get round-trip
      (content-addressed keys: ``key = SHA-1(payload)``) timed with
      the device verify ON vs OFF, best-of ``--repeat``; the ratio is
      the on-device verify cost and must stay within
      ``--auth-overhead-budget`` (gated by check_trace).
    * **poisoned-value injection under churn** — honest values are
      announced and seq-bumped, ``--kill-frac`` of the swarm churns
      (+heal), then an attacker injects bit-flipped payloads at the
      honest keys (higher seq), forged random ids, and replayed stale
      values.  The DEFENDED arm (``StoreConfig.verify``) rejects the
      forgeries inside the jit (``StoreTrace.integrity_rejects``,
      conservation exact) and discards corrupted replicas at
      get-merge: integrity ≈ 1.0.  The UNDEFENDED arm accepts them and
      its gets return corrupted bytes — the defended-vs-undefended
      curve, chaos-lookup's methodology applied to the storage plane.
      (Stale replays are rejected by seq monotonicity in BOTH arms —
      the freshness defense needs no digests, recorded as such.)
    * **pipelined host signatures** — signed host values verified
      through the :class:`~opendht_tpu.models.integrity.
      SignatureStage` in batches overlapped with device get bursts,
      plus a short open-loop serve leg admitting a SIGNED request
      class through the same stage.  Without the optional
      ``cryptography`` dep every signature figure reports null
      instead of crashing (the crawl mode's contract).

    Exit 1 if the defended arm's integrity is not exactly 1.0 or any
    leg's trace fails conservation — those are correctness statements,
    not measurements.
    """
    from opendht_tpu.models.integrity import (
        HAVE_CRYPTO, SignatureStage, content_ids, content_ids_host,
        forge_payloads, make_signed_values,
    )
    from opendht_tpu.models.serve import (
        ServeEngine, poisson_zipf_events, serve_open_loop,
    )
    from opendht_tpu.models.storage import (
        StoreConfig, announce, empty_store, get_values,
    )
    from opendht_tpu.models.swarm import (
        SwarmConfig, build_swarm, churn, heal_swarm,
    )

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])

    w = args.payload_words
    p = args.puts
    base = dict(slots=auto_slots(args, cfg),
                listen_slots=1 if args.nodes >= 4_000_000 else 4,
                max_listeners=1 << 10, payload_words=w)
    scfg_v = StoreConfig(verify=True, **base)
    scfg_u = StoreConfig(verify=False, **base)

    payloads = jax.random.bits(jax.random.PRNGKey(8), (p, w),
                               jnp.uint32)
    keys = content_ids(payloads)           # content-addressed ids
    seqs1 = jnp.ones((p,), jnp.uint32)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    # Host↔device digest parity on a sample: the two views of one id
    # must be interchangeable, or the whole plane is fiction.
    ns = min(64, p)
    digest_parity = bool(
        (np.asarray(keys[:ns]) ==
         content_ids_host(np.asarray(payloads[:ns]))).all())

    def sync(res):
        return int(np.asarray(jnp.sum(res.val[:8])))

    def conserve(tr: dict) -> bool:
        return tr["requests"] == tr["accepts_update"] \
            + tr["accepts_new"] + tr["rejects"] \
            + tr["integrity_rejects"]

    # --- stage 1: overhead A/B (honest announce+get, verify on/off)
    def roundtrip(scfg, seed):
        store = empty_store(cfg.n_nodes, scfg)
        store, rep = announce(swarm, cfg, store, scfg, keys, vals,
                              seqs1, 0, jax.random.PRNGKey(seed),
                              payloads=payloads)
        res = get_values(swarm, cfg, store, scfg, keys,
                         jax.random.PRNGKey(seed + 1))
        return rep, res

    walls = {}
    for name, scfg in (("verified", scfg_v), ("unverified", scfg_u)):
        rep, res = roundtrip(scfg, 2)      # warmup/compile
        sync(res)
        times = []
        for r in range(args.repeat):
            t0 = time.perf_counter()
            rep, res = roundtrip(scfg, 10 + 2 * r)
            sync(res)
            times.append(time.perf_counter() - t0)
        walls[name] = min(times)
        if name == "verified":
            tr = rep.trace.to_dict()
            assert conserve(tr) and tr["integrity_rejects"] == 0, tr
            hit_rate_clean = float(np.asarray(res.hit).mean())
    overhead_ratio = round(
        (walls["verified"] - walls["unverified"])
        / walls["unverified"], 4)

    # --- stage 2: poisoned-value injection under churn
    flip_pl, _flip_hit = forge_payloads(payloads,
                                        jax.random.PRNGKey(21), 1.0)
    forge_pl = jax.random.bits(jax.random.PRNGKey(22), (p, w),
                               jnp.uint32)
    forge_keys = jax.random.bits(jax.random.PRNGKey(23), (p, 5),
                                 jnp.uint32)
    churned = None
    if args.kill_frac:
        churned = churn(swarm._replace(tables=jnp.copy(swarm.tables)),
                        jax.random.PRNGKey(24), args.kill_frac, cfg)
        churned = heal_swarm(churned, cfg, jax.random.PRNGKey(25))

    def scenario(scfg, seed):
        sw = churned if churned is not None else swarm
        store = empty_store(cfg.n_nodes, scfg)
        legs = {}
        # Honest publish at seq 1, owner refresh at seq 2 (the seq
        # floor the stale replay below must fail against).
        store, rep = announce(swarm, cfg, store, scfg, keys, vals,
                              seqs1, 0, jax.random.PRNGKey(seed),
                              payloads=payloads)
        legs["honest"] = rep.trace.to_dict()
        store, rep = announce(swarm, cfg, store, scfg, keys, vals,
                              seqs1 + 1, 1, jax.random.PRNGKey(seed + 1),
                              payloads=payloads)
        legs["honest_refresh"] = rep.trace.to_dict()
        # Churn happened; the attacker injects on the healed swarm.
        store, rep = announce(sw, cfg, store, scfg, keys, vals,
                              seqs1 + 2, 2, jax.random.PRNGKey(seed + 2),
                              payloads=flip_pl)
        legs["attack_flip"] = rep.trace.to_dict()
        store, rep = announce(sw, cfg, store, scfg, forge_keys, vals,
                              seqs1, 2, jax.random.PRNGKey(seed + 3),
                              payloads=forge_pl)
        legs["attack_forge"] = rep.trace.to_dict()
        store, rep = announce(sw, cfg, store, scfg, keys, vals,
                              seqs1, 2, jax.random.PRNGKey(seed + 4),
                              payloads=payloads)
        legs["attack_replay"] = rep.trace.to_dict()
        res = get_values(sw, cfg, store, scfg, keys,
                         jax.random.PRNGKey(seed + 5))
        hit = np.asarray(res.hit)
        got = np.asarray(res.payload)
        if hit.any():
            ok_rows = (content_ids_host(got[hit])
                       == np.asarray(keys)[hit]).all(axis=1)
            integrity = round(float(ok_rows.mean()), 6)
        else:
            integrity = None
        return {"legs": legs, "integrity": integrity,
                "hit_rate": round(float(hit.mean()), 4)}

    defended = scenario(scfg_v, 40)
    undefended = scenario(scfg_u, 40)   # same seeds: same lookups

    ok = digest_parity
    for arm_name, arm in (("defended", defended),
                          ("undefended", undefended)):
        for leg_name, tr in arm["legs"].items():
            if not conserve(tr):
                print(f"bench: auth {arm_name}/{leg_name} trace does "
                      f"not conserve: {tr}", file=sys.stderr)
                ok = False
    datk = defended["legs"]["attack_flip"]
    if datk["accepts_update"] + datk["accepts_new"] != 0 \
            or datk["integrity_rejects"] == 0:
        print(f"bench: defended arm ACCEPTED forged payloads: {datk}",
              file=sys.stderr)
        ok = False
    if defended["integrity"] != 1.0:
        print(f"bench: defended integrity {defended['integrity']} != "
              f"1.0 — a forged payload entered a result set",
              file=sys.stderr)
        ok = False

    # --- stage 3: pipelined host signature verify
    n_sig = min(256, p)
    sig_batches = 4
    sig_values, _ident = make_signed_values(n_sig)
    stage = SignatureStage()
    sig_store = empty_store(cfg.n_nodes, scfg_u)
    sig_store, _rep = announce(swarm, cfg, sig_store, scfg_u, keys,
                               vals, seqs1, 0, jax.random.PRNGKey(59),
                               payloads=payloads)
    kb = max(1, p // sig_batches)
    t0 = time.perf_counter()
    for b in range(sig_batches):
        batch = (sig_values[b::sig_batches] if sig_values is not None
                 else list(range(b, n_sig, sig_batches)))
        stage.submit(batch)
        # The device burst the verify overlaps: the signed-putget read
        # leg — a real get over the announced keyset.
        chunk = keys[b * kb:(b + 1) * kb]
        if chunk.shape[0] == 0:
            chunk = keys[:kb]
        res = get_values(swarm, cfg, sig_store, scfg_u, chunk,
                         jax.random.PRNGKey(60 + b))
        sync(res)
    device_wall = time.perf_counter() - t0
    sig = stage.drain()
    sig["pipelined_wall_s"] = round(device_wall, 6)
    if sig["verify_wall_s"] is not None:
        # Overlap saved = serial (verify then device) minus pipelined.
        sig["overlap_saved_s"] = round(
            max(0.0, sig["verify_wall_s"] + device_wall
                - max(device_wall, sig["verify_wall_s"])), 6)

    # --- stage 3b: a signed request class under open-loop serve load
    srv_rate, srv_dur = 300.0, 1.0
    ts, skeys, klass = poisson_zipf_events(
        rate=srv_rate, duration=srv_dur,
        key_pool=min(args.key_pool, 512), zipf_s=1.1, seed=7)
    signed_mask = np.random.default_rng(9).random(len(ts)) < 0.25
    stage2 = SignatureStage()
    engine = ServeEngine(swarm, cfg, slots=256)
    sig_value_of = ((lambda ri: sig_values[ri % n_sig])
                    if sig_values is not None else None)
    srv = serve_open_loop(engine, ts, skeys, jax.random.PRNGKey(3),
                          klass=klass, burst=2, duration=srv_dur,
                          sig_stage=stage2, signed=signed_mask,
                          signed_value_of=sig_value_of)
    sig_serve = stage2.drain()
    sig_serve["signed_requests"] = int(signed_mask.sum())
    sig_serve["sig_submitted"] = srv["sig_submitted"]
    sig_serve["completed"] = srv["completed"]
    sig_serve["sustained_rps"] = round(srv["sustained_rps"], 1)

    out = {
        "metric": "swarm_auth_defended_integrity",
        "value": defended["integrity"],
        "unit": "fraction",
        "vs_baseline": (round(defended["integrity"]
                              - undefended["integrity"], 4)
                        if undefended["integrity"] is not None
                        and defended["integrity"] is not None
                        else None),
        "baseline_note": "vs_baseline = defended - undefended "
                         "integrity under the same poisoned-value "
                         "injection (the defense's recall gain, "
                         "chaos-lookup's convention)",
        "n_nodes": args.nodes,
        "n_puts": p,
        "payload_words": w,
        "payload_bytes": 4 * w,
        "kill_frac": args.kill_frac,
        "slots": scfg_v.slots,
        "digest_parity": digest_parity,
        "hit_rate_clean": hit_rate_clean,
        "undefended_integrity": undefended["integrity"],
        "defended_hit_rate": defended["hit_rate"],
        "undefended_hit_rate": undefended["hit_rate"],
        "integrity_rejects": sum(
            tr["integrity_rejects"]
            for tr in defended["legs"].values()),
        "verified_wall_s": round(walls["verified"], 4),
        "unverified_wall_s": round(walls["unverified"], 4),
        "overhead_ratio": overhead_ratio,
        "overhead_budget": args.auth_overhead_budget,
        "crypto_available": HAVE_CRYPTO,
        "sig_verifies_per_sec": sig["verifies_per_sec"],
        "platform": jax.devices()[0].platform,
    }
    if args.auth_out:
        obj = {
            "kind": "swarm_auth_trace",
            "bench": out,
            "digest_parity": digest_parity,
            "overhead": {
                "verified_wall_s": round(walls["verified"], 6),
                "unverified_wall_s": round(walls["unverified"], 6),
                "ratio": overhead_ratio,
                "budget": args.auth_overhead_budget,
                "repeat": args.repeat,
            },
            "arms": {"defended": defended, "undefended": undefended},
            "signature": sig,
            "serve_signed": sig_serve,
        }
        with open(args.auth_out, "w") as f:
            json.dump(obj, f)
            f.write("\n")
    print(json.dumps(out))
    if not ok:
        sys.exit(1)


def chunked_main(args):
    """Chunk-fault chaos plane on the sharded engine (ISSUE 16): do
    chunked values survive the mesh?

    A pool of ``--puts`` variable-size values (``--chunk-parts`` parts
    of ``--payload-words`` words each, hash-list content-addressed
    keys, ONE zero-length row) is driven through the routed
    announce/get twins at infinite capacity — the chaos is INJECTED,
    never ambient — in five legs per arm:

    * **clean** — exact reassembly, with the summed per-part StoreTrace
      equated to the whole-value oracle (a second identically-seeded
      routed lookup: every active part on every found node);
    * **torn_drop** — ``--chunk-fault-drop-frac`` of the values lose
      part 0 at announce (``part_drop_mask``);
    * **kill_mid** — the writer dies between parts: only parts below
      ``--chunk-fault-kill-part`` leave the NIC (``part_range``);
    * **torn_overwrite** — a full publish at seq 1, then a seq-2
      overwrite killed after part 0: the reassembly guard must refuse
      to mix generations;
    * **forge** — every part re-announced at seq 3 with ONE word of
      part ``--chunk-fault-forge-part`` bit-flipped; the DEFENDED arm
      (``StoreConfig.verify``) rejects affected rows at the get-merge
      (``_chunked_root_ok`` in-jit, ``root_rejects`` = guard-passing
      hits minus root-passing hits on the SAME store), the undefended
      arm serves garbled bytes — the defended-vs-undefended curve.

    The mesh-wide contract is MISSING, NEVER GARBLED: every torn row
    reads back missing in BOTH arms (``torn_missing_rate`` exactly
    1.0), and the defended arm serves zero garbled rows anywhere.
    A final heal leg churns ``--kill-frac`` of the swarm (+heal),
    then counts owner republish sweeps until every value — including
    the torn ones — reads back whole.  The artifact
    (``--chunked-out``, kind ``swarm_chunked_trace``) is validated by
    ``tools/check_trace.py check_chunked_obj`` and gated by
    ``tools/check_bench.py``; the bench self-validates through the
    same checker and exits 1 on any violation — these are correctness
    statements, not measurements.
    """
    from opendht_tpu.models.chunked_values import (
        chunked_content_ids, chunked_content_ids_host,
        mask_chunk_payloads,
    )
    from opendht_tpu.models.storage import StoreConfig
    from opendht_tpu.models.swarm import (
        SwarmConfig, build_swarm, churn, heal_swarm,
    )
    from opendht_tpu.parallel import make_mesh
    from opendht_tpu.parallel.sharded import sharded_lookup
    from opendht_tpu.parallel.sharded_storage import (
        sharded_announce_chunked, sharded_empty_store,
        sharded_get_chunked,
    )
    from opendht_tpu.tools.check_trace import check_chunked_obj

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])

    parts = args.chunk_parts
    w = args.payload_words
    cap = float("inf")
    base = dict(slots=args.slots or 16, listen_slots=2,
                max_listeners=1 << 6, payload_words=w)
    scfg_v = StoreConfig(verify=True, **base)
    scfg_u = StoreConfig(verify=False, **base)
    p = max(4, min(args.puts,
                   cfg.n_nodes * scfg_v.slots // (16 * parts)))

    rng = np.random.default_rng(16)
    pls_h = rng.integers(0, 1 << 32, (p, parts, w),
                         dtype=np.uint64).astype(np.uint32)
    lens_h = rng.integers(1, 4 * parts * w + 1,
                          (p,)).astype(np.uint32)
    # Pinned rows: ONE zero-length value (all zero-length values share
    # one content key — a second would collide), one sub-word, one
    # spanning every part (so every torn leg provably bites).
    lens_h[0] = 0
    if p > 1:
        lens_h[1] = 3
    lens_h[2:4] = 4 * parts * w
    payloads = jnp.asarray(pls_h)
    lengths = jnp.asarray(lens_h)
    keys = chunked_content_ids(payloads, lengths)
    vals = jnp.arange(p, dtype=jnp.uint32) + 1
    seqs = jnp.ones((p,), jnp.uint32)
    masked, _ml = mask_chunk_payloads(payloads, lengths)
    oracle = np.asarray(masked).reshape(p, parts * w)
    words = (lens_h.astype(np.int64) + 3) // 4
    n_parts_of = np.clip(-(-words // w), 1, parts)
    digest_parity = bool(
        (np.asarray(keys)
         == chunked_content_ids_host(pls_h, lens_h)).all())

    # Fault plans, shared verbatim across arms (host-side, so every
    # `affected` count below is exact, not sampled).
    kp = args.chunk_fault_kill_part
    fp = args.chunk_fault_forge_part
    tdrop = np.asarray(
        rng.random(p) < args.chunk_fault_drop_frac)
    if not tdrop.any():
        tdrop[2] = True                  # the full-span pinned row
    drop_mask = np.zeros((p, parts), bool)
    drop_mask[:, 0] = tdrop              # part 0 lost => whole value
    drop_mask_j = jnp.asarray(drop_mask)
    a_drop = int(tdrop.sum())
    kill_rows = n_parts_of > kp          # parts >= kp never sent
    a_kill = int(kill_rows.sum())
    torn_rows = n_parts_of > 1           # seq-2 overwrite died after 0
    a_torn = int(torn_rows.sum())
    forge_rows = words > fp * w          # the flipped word is LIVE
    a_forge = int(forge_rows.sum())
    forged_h = pls_h.copy()
    forged_h[:, fp, 0] ^= 0x80000000
    forged = jnp.asarray(forged_h)

    def measure(res):
        hit = np.asarray(res.hit)
        exact_rows = hit & (np.asarray(res.length) == lens_h) \
            & (np.asarray(res.payload) == oracle).all(axis=1)
        h, e = int(hit.sum()), int(exact_rows.sum())
        return hit, {"hit": h, "missing": p - h, "garbled": h - e,
                     "exact": e}

    def tsum(*trs):
        return {k: sum(t[k] for t in trs) for k in trs[0]}

    def run_arm(scfg):
        """One arm: five legs, each on a FRESH store, same PRNGKeys as
        the other arm (identical routing — the arms differ only in the
        read-side verify)."""
        legs, hits = {}, {}

        def fresh():
            return sharded_empty_store(cfg.n_nodes, scfg, mesh)

        def put(store, seed, pls=payloads, sq=seqs, now=0, **faults):
            return sharded_announce_chunked(
                swarm, cfg, store, scfg, keys, vals, sq, now,
                jax.random.PRNGKey(seed), mesh, pls, lengths,
                capacity_factor=cap, **faults)

        def get(store, seed, sc=None):
            return sharded_get_chunked(
                swarm, cfg, store, sc or scfg, keys,
                jax.random.PRNGKey(seed), mesh, parts,
                capacity_factor=cap)

        # clean
        store, rep = put(fresh(), 100)
        tr_clean = rep.trace.to_dict()
        hit, m = measure(get(store, 101))
        legs["clean"] = dict(m, affected=0, trace=tr_clean)
        hits["clean"] = hit
        # torn_drop (keep the store — it seeds the heal leg)
        store_drop, rep = put(fresh(), 110,
                              part_drop_mask=drop_mask_j)
        hit, m = measure(get(store_drop, 111))
        legs["torn_drop"] = dict(m, affected=a_drop,
                                 trace=rep.trace.to_dict())
        hits["torn_drop"] = hit
        # kill_mid
        store, rep = put(fresh(), 120, part_range=(0, kp))
        hit, m = measure(get(store, 121))
        legs["kill_mid"] = dict(m, affected=a_kill,
                                trace=rep.trace.to_dict())
        hits["kill_mid"] = hit
        # torn_overwrite
        store, rep1 = put(fresh(), 130)
        store, rep2 = put(store, 131, sq=seqs + 1, now=1,
                          part_range=(0, 1))
        hit, m = measure(get(store, 132))
        legs["torn_overwrite"] = dict(
            m, affected=a_torn, trace=tsum(rep1.trace.to_dict(),
                                           rep2.trace.to_dict()))
        hits["torn_overwrite"] = hit
        # forge
        store, rep1 = put(fresh(), 140)
        store, rep2 = put(store, 141, pls=forged, sq=seqs + 2, now=2)
        res = get(store, 142)
        hit, m = measure(res)
        legs["forge"] = dict(m, affected=a_forge,
                             trace=tsum(rep1.trace.to_dict(),
                                        rep2.trace.to_dict()))
        hits["forge"] = hit
        if scfg.verify:
            # root_rejects = rows that pass the reassembly guard but
            # fail the hash-list root — measured on the SAME store,
            # same routing seed, verify off vs on.
            guard_hit = np.asarray(get(store, 142, sc=scfg_u).hit)
            legs["forge"]["root_rejects"] = \
                int(guard_hit.sum()) - m["hit"]
        h_tot = sum(lg["hit"] for lg in legs.values())
        e_tot = sum(lg["exact"] for lg in legs.values())
        integrity = 1.0 if h_tot == 0 else e_tot / h_tot
        return {"integrity": integrity, "legs": legs}, hits, store_drop

    defended, hits_d, store_drop = run_arm(scfg_v)
    undefended, hits_u, _ = run_arm(scfg_u)

    # Whole-value conservation oracle for the clean leg: the same
    # seeded routed lookup yields the same found set; every value
    # places each ACTIVE part (words > j*W, part 0 always) on every
    # found node — at infinite capacity on an empty store that is
    # exactly the summed requests, every one a fresh accept.
    res_o = sharded_lookup(swarm, cfg, keys, jax.random.PRNGKey(100),
                           mesh, cap)
    found_per_row = (np.asarray(res_o.found) >= 0).sum(axis=1)
    oracle_req = sum(
        int(found_per_row[(words > j * w) | (j == 0)].sum())
        for j in range(parts))
    tr_clean = defended["legs"]["clean"]["trace"]
    conservation = {"requests": tr_clean["requests"],
                    "oracle_requests": oracle_req,
                    "accepts_new": tr_clean["accepts_new"],
                    "oracle_accepts_new": oracle_req}

    # Torn rows must read MISSING in both arms — rate over every
    # affected row of every torn leg.
    torn_n = torn_miss = 0
    for leg, rows in (("torn_drop", tdrop), ("kill_mid", kill_rows),
                      ("torn_overwrite", torn_rows)):
        for hits in (hits_d, hits_u):
            torn_n += int(rows.sum())
            torn_miss += int((~hits[leg][rows]).sum())
    torn_missing_rate = torn_miss / torn_n if torn_n else 1.0

    # Heal: the torn_drop store under churn (+healed routing), owner
    # republish sweeps until every value reads back whole.
    sw_heal = swarm
    if args.kill_frac:
        sw_heal = churn(swarm._replace(tables=jnp.copy(swarm.tables)),
                        jax.random.PRNGKey(150), args.kill_frac, cfg)
        sw_heal = heal_swarm(sw_heal, cfg, jax.random.PRNGKey(151))
    _hit, m = measure(sharded_get_chunked(
        sw_heal, cfg, store_drop, scfg_v, keys,
        jax.random.PRNGKey(152), mesh, parts, capacity_factor=cap))
    pre_hit = m["hit"]
    sweeps = 0
    for s in range(1, 9):
        store_drop, _rep = sharded_announce_chunked(
            sw_heal, cfg, store_drop, scfg_v, keys, vals, seqs,
            10 + s, jax.random.PRNGKey(160 + s), mesh, payloads,
            lengths, capacity_factor=cap)
        _hit, m = measure(sharded_get_chunked(
            sw_heal, cfg, store_drop, scfg_v, keys,
            jax.random.PRNGKey(170 + s), mesh, parts,
            capacity_factor=cap))
        sweeps = s
        if m["hit"] == p:
            break
    heal = {"pre_hit": pre_hit, "post_hit": m["hit"],
            "sweeps": sweeps, "post_garbled": m["garbled"]}

    d_int = defended["integrity"]
    u_int = undefended["integrity"]
    g_total = sum(lg["garbled"]
                  for lg in defended["legs"].values())
    out = {
        "metric": "swarm_chunked_defended_integrity",
        "value": d_int,
        "unit": "frac",
        "vs_baseline": round(d_int - u_int, 4),
        "baseline_note": "vs_baseline = defended - undefended "
                         "integrity under the same chunk-fault "
                         "injection (the get-merge hash-list "
                         "defense's recall gain, auth mode's "
                         "convention)",
        "n_nodes": args.nodes,
        "n_devices": n_dev,
        "values": p,
        "parts": parts,
        "payload_words": w,
        "kill_frac": args.kill_frac,
        "chunk_fault_drop_frac": args.chunk_fault_drop_frac,
        "chunk_fault_kill_part": kp,
        "chunk_fault_forge_part": fp,
        "digest_parity": digest_parity,
        "undefended_integrity": u_int,
        "garbled_reads": g_total,
        "undefended_garbled_reads": sum(
            lg["garbled"] for lg in undefended["legs"].values()),
        "torn_missing_rate": torn_missing_rate,
        "torn_affected": a_drop + a_kill + a_torn,
        "forge_affected": a_forge,
        "root_rejects": defended["legs"]["forge"]["root_rejects"],
        "heal_pre_hit": pre_hit,
        "heal_sweeps": sweeps,
        "platform": jax.devices()[0].platform,
    }
    obj = {
        "kind": "swarm_chunked_trace",
        "bench": out,
        "params": {"values": p, "parts": parts, "payload_words": w,
                   "nodes": args.nodes},
        "digest_parity": digest_parity,
        "conservation": conservation,
        "arms": {"defended": defended, "undefended": undefended},
        "heal": heal,
    }
    # Self-validate through the gate's own checker: reassembly
    # exactness and missing-never-garbled are correctness statements —
    # a bench that fails them must exit 1 even with no --chunked-out.
    errs = check_chunked_obj(obj)
    for e in errs:
        print(f"bench: chunked {e}", file=sys.stderr)
    if args.chunked_out:
        with open(args.chunked_out, "w") as f:
            json.dump(obj, f)
            f.write("\n")
    print(json.dumps(out))
    if errs:
        sys.exit(1)


def serve_main(args):
    """Open-loop serve: the per-request latency plane (ROADMAP #2).

    Poisson(``--arrival-rate``) arrivals over ``--duration`` seconds
    with Zipf(``--zipf``)-popular keys are admitted as micro-batches
    into recycled slots of the resident serve engine
    (models/serve.py): finished rows' slots admit NEW requests
    mid-flight instead of compacting away.  The reported number is not
    throughput but the arrival→completion latency DISTRIBUTION —
    p50/p95/p99/p99.9 derived from the latency histogram's bucket
    bounds (``utils.metrics.Histogram.quantile``) — plus sustained
    req/s, queue depth and slot occupancy, with the SLO gauge set
    (target / violation ratio / error-budget burn rate) published
    through the PR-3 Prometheus registry.  The reference sheds this
    exact workload at 1,600 req/s global inbound
    (include/opendht/network_engine.h:462) — vs_baseline divides by
    that cap.  ``--serve-out`` dumps the artifact
    ``tools/check_trace.py`` validates (lifecycle conservation,
    histogram⇄row consistency, quantiles inside their buckets).
    """
    from opendht_tpu.models.serve import (
        AdmissionControl, ResidentServeEngine, ServeEngine,
        ServeOverloadError, ShardedResidentServeEngine,
        ShardedServeEngine, autotune_serve_slots, measure_round_wall,
        poisson_zipf_events, serve_open_loop, serve_resident,
    )
    from opendht_tpu.models.swarm import (SwarmConfig, build_swarm,
                                          burst_schedule)
    from opendht_tpu.obs.latency import (LatencyPlane,
                                         publish_hop_histogram)
    from opendht_tpu.utils.metrics import Histogram, MetricsRegistry

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])

    # None = flag untouched → the serve default (1.1); an EXPLICIT
    # --zipf 0 means uniform keys, exactly as poisson_zipf_events
    # documents — never silently overridden.
    zipf_s = 1.1 if args.zipf is None else args.zipf
    ts, keys, klass = poisson_zipf_events(
        rate=args.arrival_rate, duration=args.duration,
        key_pool=args.key_pool, zipf_s=zipf_s, seed=7)

    # --serve-slots auto: measure one round of a fully-occupied probe
    # engine, size the plane by Little's law (autotune_serve_slots).
    round_wall_probe = None
    if args.serve_slots == "auto":
        # Two-pass probe: the per-round wall grows with slot width, so
        # a plane sized from a narrow probe under-estimates service
        # time exactly when it picks a wide plane.  Measure at 512,
        # size, then RE-measure at the candidate width (capped — a
        # 65k-row probe would cost more than it informs) and re-size
        # once; widths only move between two adjacent powers of two,
        # so one refinement converges.
        probe_w = 512
        round_wall_probe = measure_round_wall(swarm, cfg,
                                              slots=probe_w)
        args.serve_slots = autotune_serve_slots(
            cfg, args.arrival_rate, round_wall_probe)
        if args.serve_slots > probe_w:
            probe_w = min(args.serve_slots, 4096)
            round_wall_probe = measure_round_wall(swarm, cfg,
                                                  slots=probe_w)
            args.serve_slots = autotune_serve_slots(
                cfg, args.arrival_rate, round_wall_probe)
        slots_mode = "auto"
        print(f"bench: --serve-slots auto -> {args.serve_slots} "
              f"(round wall {round_wall_probe * 1e3:.2f} ms at width "
              f"{probe_w}, ~{burst_schedule(cfg) + 1} rounds/request)",
              file=sys.stderr)
    else:
        slots_mode = "fixed"

    resident = args.serve_engine == "resident"
    res_kw = dict(
        ring_slots=args.ring_slots or None,
        rounds_per_iter=args.resident_rounds)
    if args.sharded:
        from opendht_tpu.parallel import make_mesh
        n_dev = len(jax.devices())
        mesh = make_mesh(n_dev)
        if resident:
            if args.rung_select:
                print("bench: --rung-select is local-engine only (the "
                      "routed step prices its own exchange); ignored "
                      "under --sharded", file=sys.stderr)
            engine = ShardedResidentServeEngine(
                swarm, cfg, args.serve_slots, mesh,
                capacity_factor=2.0, cache_slots=args.serve_cache,
                **res_kw)
        else:
            engine = ShardedServeEngine(
                swarm, cfg, slots=args.serve_slots, mesh=mesh,
                capacity_factor=2.0, cache_slots=args.serve_cache)
    else:
        n_dev = 1
        if resident:
            engine = ResidentServeEngine(
                swarm, cfg, slots=args.serve_slots,
                cache_slots=args.serve_cache,
                rung_block=args.rung_select or None, **res_kw)
        else:
            engine = ServeEngine(swarm, cfg, slots=args.serve_slots,
                                 cache_slots=args.serve_cache)
    admission = None
    if args.admission != "none":
        admission = AdmissionControl(rate=args.admit_rate,
                                     burst=args.admit_burst,
                                     policy=args.admission,
                                     per_key_rate=args.admit_key_rate,
                                     max_keys=args.admit_max_keys)
    try:
        if resident:
            rep = serve_resident(
                engine, ts, keys, jax.random.PRNGKey(3), klass=klass,
                duration=args.duration, admission=admission,
                host_orchestration_budget=args.resident_orch_budget)
        else:
            rep = serve_open_loop(engine, ts, keys,
                                  jax.random.PRNGKey(3),
                                  klass=klass, burst=args.serve_burst,
                                  duration=args.duration,
                                  admission=admission)
    except ServeOverloadError as e:
        print(f"bench: {e}", file=sys.stderr)
        sys.exit(2)

    lat = rep["latency_s"]
    slo_s = args.slo_ms / 1e3
    registry = MetricsRegistry()
    plane = LatencyPlane(registry, prefix="dht_serve_request",
                         label_names=("klass",), slo_target_s=slo_s)
    for s, k in zip(lat, rep["klass"]):
        plane.observe(float(s), klass=str(k))
    publish_hop_histogram(
        registry, np.bincount(np.clip(rep["hops"], 0, cfg.max_steps),
                              minlength=cfg.max_steps + 1))
    # Artifact histogram: one UNlabelled latency distribution (the
    # checker's count-conservation target), Prometheus latency bounds.
    bounds = list(Histogram.LATENCY_BUCKETS_S)
    bidx = np.searchsorted(bounds, lat, side="left") if len(lat) \
        else np.zeros((0,), np.int64)
    counts = np.bincount(bidx, minlength=len(bounds) + 1)
    # Headline quantiles DERIVED FROM THE BUCKET BOUNDS (linear
    # interpolation inside the holding bucket — Histogram.quantile):
    # the artifact's histogram can always reproduce them, which is
    # exactly what check_trace gates.  Raw-sample percentiles ride
    # along for reference.
    agg = Histogram("serve_latency_agg", "", buckets=bounds)
    agg.observe_bulk([int(c) for c in counts], float(lat.sum()))
    # None (JSON null), never NaN, with zero completions: json.dumps
    # would happily emit the literal NaN, which is not JSON.
    quants = {name: (round(agg.quantile(q), 6) if len(lat) else None)
              for name, q in (("p50", 0.50), ("p95", 0.95),
                              ("p99", 0.99), ("p999", 0.999))}
    raw = {f"{name}_raw": (round(float(np.percentile(lat, 100 * q)), 6)
                           if len(lat) else None)
           for name, q in (("p50", 0.50), ("p95", 0.95),
                           ("p99", 0.99), ("p999", 0.999))}
    offered = rep["admitted"] + rep["never_admitted"] + rep["shed"]

    out = {
        "metric": "swarm_serve_req_per_sec",
        "value": round(rep["sustained_rps"], 1),
        "unit": "req/s",
        # The reference's global inbound rate limiter caps the stream
        # this mode models at 1,600 req/s (network_engine.h:462).
        "vs_baseline": round(rep["sustained_rps"] / 1600.0, 2),
        "baseline_note": "vs the reference's 1600 req/s global inbound "
                         "rate cap (include/opendht/network_engine.h:"
                         "462)",
        "n_nodes": args.nodes,
        "arrival_rate": args.arrival_rate,
        "duration_s": args.duration,
        "elapsed_s": round(rep["elapsed_s"], 4),
        "serve_slots": rep["slots"],
        "admit_cap": rep["admit_cap"],
        "burst": rep["burst"],
        "rounds": rep["rounds"],
        "admitted": rep["admitted"],
        "completed": rep["completed"],
        "expired": rep["expired"],
        "in_flight": rep["in_flight"],
        "shed": rep["shed"],
        "sharded": bool(args.sharded),
        "serve_engine": args.serve_engine,
        "n_devices": n_dev,
        "serve_slots_mode": slots_mode,
        "round_wall_probe_s": (round(round_wall_probe, 6)
                               if round_wall_probe is not None
                               else None),
        "cache_slots": rep["cache_slots"],
        "cache_hits": rep["cache_hits"],
        "cache_misses": rep["cache_misses"],
        "cache_hit_frac": (round(rep["cache_hits"] / rep["admitted"],
                                 4) if rep["admitted"] else None),
        "degraded_hits": rep["degraded_hits"],
        "admission_policy": rep["admission_policy"],
        "admit_rate": (args.admit_rate if args.admission != "none"
                       else None),
        "done_frac": round(rep["completed"] / offered, 6)
        if offered else 0.0,
        "found_nonempty_frac": round(
            float(rep["found_nonempty"].mean()), 4)
        if rep["completed"] else None,
        "median_hops": float(np.median(rep["hops"]))
        if rep["completed"] else None,
        "latency_p50_s": quants["p50"],
        "latency_p95_s": quants["p95"],
        "latency_p99_s": quants["p99"],
        "latency_p999_s": quants["p999"],
        "latency_mean_s": round(float(lat.mean()), 6)
        if len(lat) else None,
        **{f"latency_{k}_s": v for k, v in raw.items()},
        "queue_depth_mean": round(rep["queue_depth_mean"], 2),
        "queue_depth_max": rep["queue_depth_max"],
        "slot_occupancy_frac": round(rep["slot_occupancy_frac"], 4),
        "slo_target_s": slo_s,
        "slo_violation_ratio": round(plane.violation_ratio, 6),
        "slo_error_budget_burn_rate": round(plane.burn_rate, 3),
        "zipf_s": zipf_s,
        "key_pool": args.key_pool,
        "platform": jax.devices()[0].platform,
    }
    if resident:
        from opendht_tpu.obs.timeline import (ResidentPlane,
                                              resident_summary)
        ResidentPlane(registry).publish_run(rep)
        rs = resident_summary(rep)
        out["resident"] = {
            "host_orchestration_frac":
                round(rs["host_orchestration_frac"], 6),
            "overlap_frac": round(rs["overlap_frac"], 6),
            "iterations": rs["iterations"],
            "device_rounds": rs["device_rounds"],
            "ring_shed": rs["ring_shed"],
            "rung_select": rs["rung_select"],
            "exchange_mb": round(rs["exchange_mb"], 3),
        }
    if args.serve_out:
        per_class = {}
        for cls in sorted(set(map(str, rep["klass"]))):
            m = rep["klass"] == cls
            per_class[cls] = {
                "count": int(m.sum()),
                "p50_s": round(plane.quantile(0.50, klass=cls), 6),
                "p99_s": round(plane.quantile(0.99, klass=cls), 6),
            }
        obj = {
            "kind": "swarm_serve_trace",
            "bench": out,
            "lifecycle": {
                "admitted": rep["admitted"],
                "completed": rep["completed"],
                "expired": rep["expired"],
                "in_flight": rep["in_flight"],
                "never_admitted": rep["never_admitted"],
                "shed": rep["shed"],
                "cache_hits": rep["cache_hits"],
            },
            "latency_histogram": {
                "bounds": bounds,
                "counts": [int(c) for c in counts],
                "sum": round(float(lat.sum()), 6),
                "count": int(len(lat)),
            },
            "latency_quantiles_s": quants,
            "per_class": per_class,
            "burst_marks": [[int(r), round(w, 6)]
                            for r, w in rep["burst_marks"]],
            "metrics_prometheus": registry.render_prometheus(),
        }
        if resident:
            # The resident block the checker's resident leg gates:
            # ring conservation, depth bounds, orchestration share
            # vs the recorded budget, in-jit rung counts.
            obj["resident"] = dict(rep["resident"],
                                   summary=resident_summary(rep))
        if rep["cache_slots"]:
            # Cache block: hit/miss accounting plus the hit SERVICE-
            # rounds histogram — a hit completes in zero lookup
            # rounds by construction (it never occupied a slot), so
            # every hit sample must land in the first bucket; the
            # checker re-derives both from the per-request arrays'
            # invariant (service_rounds == 0 iff cache hit).
            sr = rep["service_rounds"]
            hit_sr = sr[sr == 0]
            obj["cache"] = {
                "slots": rep["cache_slots"],
                "hits": rep["cache_hits"],
                "misses": rep["cache_misses"],
                "degraded_hits": rep["degraded_hits"],
                "hit_rounds_histogram": {
                    "bounds": [0.0, 1.0],
                    "counts": [int(len(hit_sr)), 0, 0],
                },
            }
        with open(args.serve_out, "w") as f:
            json.dump(obj, f)
            f.write("\n")
    print(json.dumps(out))


def chaos_lookup_main(args):
    """Adversarial LOOKUP survival: the routing half's chaos leg.

    PR 1's --mode chaos proved the storage path degrades gracefully;
    this leg proves the same for the lookup path under the fault model
    storage never had — Byzantine responders (``--byzantine-frac`` of
    nodes answer with poisoned closest-node windows, random or
    eclipse-targeted per ``--poison``) on top of mass death
    (``--kill-frac``, with bucket maintenance healing the tables — the
    storage chaos leg's convention) and in-transit reply loss
    (``--drop-frac``).  S/Kademlia's point (PAPERS.md): lookup
    correctness under adversarial RESPONDERS, not just node loss, is
    what a production DHT must prove.

    Publishes one JSON row with a degradation CURVE across the
    (kill × byzantine × drop) grid — recall@8 / done_frac /
    median_hops per leg, all against the clean-swarm reference — plus
    the defended-vs-undefended headline pair and the defense's
    conviction precision/recall (strike/blacklist state,
    models/swarm.py chaos_step_impl).  Recall is measured against the
    true 8 closest HONEST alive nodes: convicted liars are excluded by
    design, exactly like the host engine refusing blacklisted peers.
    """
    from opendht_tpu.models.swarm import (
        LookupFaults, LookupResult, SwarmConfig, build_swarm,
        chaos_lookup, churn, corrupt_swarm, heal_swarm, honest_recall,
    )

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    kw["merge_impl"] = args.merge_impl
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])
    targets = jax.random.bits(jax.random.PRNGKey(1),
                              (args.lookups, 5), jnp.uint32)
    kf, bf, df = args.kill_frac, args.byzantine_frac, args.drop_frac
    eclipse = args.poison == "eclipse"

    # Kill+heal once per distinct kill fraction (heal_swarm DONATES
    # its table buffer, so the healed swarm gets its own copy and the
    # clean base stays valid for the other grid legs).
    healed = None
    if kf:
        healed = churn(swarm._replace(tables=jnp.copy(swarm.tables)),
                       jax.random.PRNGKey(2), kf, cfg)
        healed = heal_swarm(healed, cfg, jax.random.PRNGKey(3))

    captured = {}

    def leg(kill, byz, drop, defend=True, collect=False):
        sw = healed if kill else swarm
        if byz:
            sw = corrupt_swarm(sw, jax.random.PRNGKey(4), byz, cfg)
        faults = LookupFaults(drop_frac=drop, eclipse=eclipse, seed=11,
                              defend=defend)
        t0 = time.perf_counter()
        if collect:
            res, strikes, trace = chaos_lookup(
                sw, cfg, targets, jax.random.PRNGKey(5), faults,
                collect_trace=True)
            captured["trace"], captured["hops"] = trace, res.hops
        else:
            res, strikes = chaos_lookup(sw, cfg, targets,
                                        jax.random.PRNGKey(5), faults)
        _ = int(np.asarray(jnp.sum(res.found[:, 0])))   # completion
        dt = time.perf_counter() - t0
        # Recall vs the true 8 closest honest alive nodes, sampled.
        m = min(args.recall_sample, args.lookups)
        sample = LookupResult(found=res.found[:m], hops=res.hops[:m],
                              done=res.done[:m])
        recall = float(jnp.mean(honest_recall(sw, cfg, sample,
                                              targets[:m])))
        row = {"kill_frac": kill, "byzantine_frac": byz,
               "drop_frac": drop, "defend": defend,
               "recall_at_8": round(recall, 4),
               "done_frac": float(np.asarray(res.done).mean()),
               "median_hops": float(np.median(np.asarray(res.hops))),
               "wall_s": round(dt, 3)}
        if byz and defend:
            # Conviction stats only exist where the defense ran —
            # undefended legs never update strike state — and only
            # ALIVE nodes are in scope: dead ones are never solicited,
            # so they can neither offend nor be convicted and would
            # only dilute the denominators by ~kill_frac.
            conv = np.asarray(strikes) >= faults.strike_limit
            byz_m = np.asarray(sw.byzantine)
            alive_m = np.asarray(sw.alive)
            row["convicted_byzantine_frac"] = round(
                float(conv[byz_m & alive_m].mean()), 4)
            row["convicted_honest_frac"] = round(
                float(conv[~byz_m & alive_m].mean()), 6)
        return row

    curve = [leg(0.0, 0.0, 0.0),
             leg(kf, 0.0, 0.0),
             leg(0.0, bf, 0.0),
             leg(0.0, 0.0, df)]
    # The headline (full-fault) leg carries the flight recorder when
    # --trace-out is set: its per-round poison/strike/conviction rows
    # are what EXPLAIN the degradation numbers below.
    headline = leg(kf, bf, df, collect=bool(args.trace_out))
    undefended = leg(kf, bf, df, defend=False)
    clean = curve[0]

    out = {
        "metric": "swarm_chaos_lookup_recall_at_8",
        "value": headline["recall_at_8"],
        "unit": "fraction",
        "vs_baseline": round(headline["recall_at_8"]
                             / max(clean["recall_at_8"], 1e-9), 4),
        "baseline_note": "vs_baseline = survival ratio vs the clean-"
                         "swarm leg of the same grid (1.0 = adversarial"
                         " conditions cost nothing)",
        "n_nodes": cfg.n_nodes,
        "n_lookups": args.lookups,
        "kill_frac": kf,
        "byzantine_frac": bf,
        "drop_frac": df,
        "poison": args.poison,
        "headline": headline,
        "undefended": undefended,
        "degradation_curve": curve,
        "defense": {"strike_limit": LookupFaults().strike_limit,
                    "undefended_recall": undefended["recall_at_8"],
                    "defense_recall_gain": round(
                        headline["recall_at_8"]
                        - undefended["recall_at_8"], 4)},
        "platform": jax.devices()[0].platform,
    }
    if args.trace_out:
        dump_trace(args.trace_out, out, captured["trace"],
                   args.lookups, captured["hops"], cfg.max_steps)
    print(json.dumps(out))


def hotshard_main(args):
    """Zipf hot-shard contention under the bounded-capacity transport.

    Lookup *targets* (not churn gets) drawn Zipf-skewed from a hot key
    set, routed under the sharded transport's per-shard capacity rule
    emulated with logical shards on one chip
    (opendht_tpu.parallel.sharded.contended_lookup).  Reports the
    capacity-drop fraction and convergence-round inflation at
    capacity_factor 1 / 2 / 4 — the data behind the default 2.0.  The
    load being modeled: the reference sheds inbound traffic at 1600
    req/s global / 200 per-IP
    (/root/reference/include/opendht/network_engine.h:462).
    """
    from opendht_tpu.models.swarm import SwarmConfig, build_swarm
    from opendht_tpu.parallel.sharded import contended_lookup

    kw = {} if args.aug == "auto" else {"aug_tables": args.aug == "on"}
    cfg = SwarmConfig.for_nodes(args.nodes, **kw)
    swarm = build_swarm(jax.random.PRNGKey(0), cfg)
    _ = np.asarray(swarm.tables[:1, :1])

    l = args.lookups
    s = args.zipf if args.zipf > 0 else 1.2
    p = max(64, min(args.puts, l))
    hot = jax.random.bits(jax.random.PRNGKey(1), (p, 5), jnp.uint32)
    rnk = np.arange(1, p + 1, dtype=np.float64)
    prob = rnk ** -s
    prob /= prob.sum()
    draw = np.random.default_rng(9).choice(p, size=l, p=prob)
    targets = hot[jnp.asarray(draw)]

    def run(cf, seed):
        res, dropped, attempted = contended_lookup(
            swarm, cfg, targets, jax.random.PRNGKey(seed), args.shards,
            cf)
        _ = int(np.asarray(jnp.sum(res.found[:, 0])))
        return (float(np.asarray(dropped) / max(1, int(attempted))),
                float(np.asarray(res.hops).mean()),
                float(np.asarray(res.done).mean()))

    base_drop, base_rounds, base_done = run(float("inf"), 7)
    rows = {}
    for cf in (1.0, 2.0, 4.0):
        drop, rounds, done = run(cf, 7)
        rows[cf] = {"drop_frac": round(drop, 4),
                    "mean_rounds": round(rounds, 3),
                    "rounds_inflation": round(rounds / base_rounds, 3),
                    "done_frac": round(done, 4)}

    out = {
        "metric": "hotshard_drop_frac_cf2",
        "value": rows[2.0]["drop_frac"],
        "unit": "fraction",
        "vs_baseline": rows[2.0]["rounds_inflation"],
        "baseline_note": "vs_baseline = convergence-round inflation at "
                         "capacity_factor 2 vs uncontended transport",
        "n_nodes": args.nodes,
        "n_lookups": l,
        "zipf_s": s,
        "hot_keys": p,
        "logical_shards": args.shards,
        "uncontended_mean_rounds": round(base_rounds, 3),
        "by_capacity_factor": {str(k): v for k, v in rows.items()},
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
