# opendht_tpu build/test entry points (the reference ships CMake +
# autotools + MSVC, ref CMakeLists.txt:17-22; here the Python package is
# the product and the only compiled artifact is the native hot path).

NATIVE_SRC := opendht_tpu/native/dhtcore.cpp

.PHONY: all native test bench lint gate profile clean

all: native

native:
	python -c "from opendht_tpu import native; assert native.available(); print('libdhtcore ready')"

test:
	python -m pytest tests/ -q

# Static device-invariant analyzer (README "Static analysis").  Six
# planes: the pure-AST lint (jit hygiene, donated-reuse, ledger
# registry drift — no JAX import), the package-wide lock-discipline
# plane (write-outside-lock, check-then-act guard reads, cross-class
# lock-order cycles — no JAX import), the lowering plane (every
# ledger ENTRY_POINTS jit is lowered from its recorded abstract
# shapes and declared donation must materialize as REAL
# input<->output aliasing in the compiled executable; no f64, no host
# callbacks), the jaxpr interval prover (narrowing casts and u8/u16
# accumulates proven wrap-free from the same avals), the
# specialization-budget sweep (each budgeted jit's _cache_size held
# to the declared ladder budget), and the strict-mode replay (tier-1
# subset under jax_transfer_guard=disallow + rank_promotion=raise +
# debug_nans).  A stale-pragma pass then re-judges every suppression
# against the pre-suppression findings, and a one-line summary
# (findings per plane, pragma count, budget table) closes the log.
# Exit 0 = clean; any finding (unsuppressed by a justified
# `# graftlint: disable=<rule> (<reason>)` pragma) is a failure.
lint:
	python -m opendht_tpu.tools.graftlint --plane all

bench:
	python bench.py

# Pre-snapshot gate: the full test suite, the driver's multichip dry
# run, and a small-size bench on whatever accelerator is present —
# bench.py's EXACT code path (incl. the recall kernel config) at sizes
# that finish in ~a minute.  A red gate means do not snapshot: rounds
# 1 and 2 shipped rc=1 benches precisely because nothing ran this
# before handing the repo to the driver.  The chaos legs exercise
# fault injection on every PR, not just when someone remembers:
# storage (mid-republish mass death + exchange loss + the listener
# lifecycle) and lookup (Byzantine responders + reply loss + the
# strike/blacklist defense, defended vs undefended).
# The 100k leg runs with the flight recorder ON (--trace-out) and the
# artifact is then validated: parses, round counters monotone,
# consistent with the reported done_frac/recall, and the round-9
# phase-attribution fields (init/loop/finalize split + per-round wall
# p50) self-consistent — a bench whose trace cannot explain its own
# numbers must not gate green.  The same artifact then gates PERF:
# check_bench fails if lookups/s drops >5% below the recorded r14 row
# (BENCH_GATE_r14.json — the round-18 narrowed-plane rank merge:
# 20,095.1 lookups/s = 2.02x the r06 sort-free-core row it graduated
# from; BENCH_GATE_r05/r06.json stay for history; same-platform rate
# comparison; recall_at_8/done_frac/median_hops gate on any platform).
# The ledger leg additionally validates the round-18 width-laddered
# attribution table (round_phases_laddered: prefix-equivalent, rung
# recorded, rows self-consistent) and the committed LEDGER_r14.json
# is re-validated so the record can never rot.
# The merge-equivalence leg (tests/test_merge_equivalence.py, explicit
# below so a red merge can never hide behind an unrelated collection
# error in the full run) re-proves the rank merge and the Pallas
# round kernel bit-identical to the two-pass sorted reference on
# adversarial inputs; the compaction-equivalence leg
# (tests/test_compaction.py, riding the `test` prerequisite so it
# runs exactly once) re-proves the straggler-harvesting ladder is
# bit-identical to the uncompacted engines (plain, traced, chaos,
# sharded); the dryrun asserts both on the mesh.
# The 100k leg also runs the COST LEDGER (--ledger-out): per-kernel
# cost attribution + the round sub-phase A/B table, validated by
# check_trace (rows must sum to round_wall_p50 ±10%, peak HBM ≥ live,
# compile count 0 in the clocked attribution pass) and priced by
# roofline (compute/memory/gather-issue verdict per phase).  The
# repub-profile leg prices one republish sweep end-to-end (per-value
# lookup vs store-insert vs host orchestration, rows summing to the
# sweep wall — the ROADMAP #1 artifact) and gates it the same way.
# The SERVE leg (round 11) runs a short open-loop Poisson/Zipf stream
# through the slot-recycled serve engine: check_trace validates the
# artifact's lifecycle conservation (admitted == completed +
# in-flight), histogram⇄row consistency and bucket-derived quantiles;
# check_bench gates sustained req/s (0.95x floor) and tail latency
# (1.5x p99 ceiling) against the recorded BENCH_GATE_r07.json row.
# The MONITOR legs (round 12): the one-shot crawl row gates a 0.99x
# coverage floor against the recorded BENCH_GATE_r08.json (previously
# the only bench mode with no regression gate); the small monitor leg
# (16k nodes, 2 sweeps under kill 0.05 after the initial crawl) runs
# the continuous incremental-crawl engine and its artifact must pass
# check_trace (freshness conservation, detection lag within the
# stated sweep-period bound, hop histogram inside the analytic-model
# band — the repo's first model-based fidelity gate) and check_bench
# (coverage floor + lag bound vs the recorded MONITOR_GATE_r08.json);
# the checked-in 1M acceptance artifact MONITOR_r08.json is
# re-validated so the committed record can never rot.
# The SOAK leg (round 15): the always-on node — serve + republish +
# monitor + listener maintenance in ONE slot plane, churn every second
# plus a contiguous keyspace outage mid-run, and the maintenance-off
# A/B arm on the same schedule.  check_trace proves the artifact's
# conservation planes (per-interval serve+maintenance slot-rounds ==
# total dispatched, lifecycle conservation per work class at every
# interval boundary, device work-class plane == host bookkeeping,
# monitor freshness identities + lag bound, value survival above the
# scenario-derived floor, interference ledger reproducible from the
# embedded timelines); check_bench floors the rate (0.90x — the open
# loop's scenario response is noisier than a closed bench; quality
# gates are absolute) and ceilings p99 at 2.0x vs the recorded
# BENCH_GATE_r11.json.  The committed 1M/60s acceptance artifact
# SOAK_r11.json is re-validated so the record can never rot.
# The INDEX leg (round 14): a small device-PHT build + Zipf range
# scans through the batched trie engine; check_trace proves the
# artifact's structural invariants (leaf occupancy <= 16, split
# accounting conservation, probe rounds within the binary-search
# bound, EXACT recall vs the sequential host-PHT oracle) and
# check_bench gates the scan rate (0.95x floor, same-platform) plus
# the any-platform exactness gates against BENCH_GATE_r10.json; the
# checked-in 1M acceptance artifact INDEX_r10.json is re-validated so
# the committed record can never rot.
# The LINT leg runs FIRST: perf artifacts must never be recorded from
# an unlinted tree (a dropped donation or implicit per-round transfer
# would silently tax every number the gate then blesses).
gate: lint test
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
	python -m pytest tests/test_merge_equivalence.py -q
	python bench.py --nodes 100000 --lookups 20000 --repeat 2 --recall-sample 256 --trace-out /tmp/trace.json --ledger-out /tmp/ledger.json
	python -m opendht_tpu.tools.check_trace /tmp/trace.json
	python -m opendht_tpu.tools.check_trace /tmp/ledger.json
	python -m opendht_tpu.tools.roofline /tmp/ledger.json
	python -m opendht_tpu.tools.check_bench /tmp/trace.json BENCH_GATE_r14.json --min-ratio 0.90
	python -m opendht_tpu.tools.check_trace LEDGER_r14.json
# ^ 0.90 rate floor for the lookups leg from round 18 on: the merge
#   attack halved the leg's timed wall to ~1.1 s, and back-to-back
#   clean runs measured a 13% spread (17.8k-20.1k lookups/s) at that
#   duration — the same noise-band rationale as the index leg.  The
#   quality gates (recall_at_8/done_frac/median_hops) stay absolute.
	python bench.py --mode repub-profile --nodes 16384 --puts 2048 --repeat 2 --ledger-out /tmp/ledger_repub.json
	python -m opendht_tpu.tools.check_trace /tmp/ledger_repub.json
	python bench.py --mode serve --nodes 16384 --arrival-rate 2000 --duration 3 --serve-slots 1024 --key-pool 1024 --serve-out /tmp/serve.json
	python -m opendht_tpu.tools.check_trace /tmp/serve.json
	python -m opendht_tpu.tools.check_bench /tmp/serve.json BENCH_GATE_r07.json
# Round-16 serving legs.  (1) CACHE-ON serve at the same 16k/Zipf
# schedule shape as the r07 leg but 4x the arrival rate: the device
# hot-key result cache answers the Zipf head at admission (zero
# rounds, zero slots), so sustained rate must hold >= 3x the r07
# 1,930 req/s row (recorded here: 7,580 req/s, hit frac 0.78, p99
# 242 ms).  check_trace proves the cache conservation planes (hits +
# misses == admitted, lifecycle cache_hits == cache block hits, every
# hit sample in the FIRST service-rounds bucket); check_bench floors
# rate/hit-frac and ceilings p99 vs BENCH_GATE_r12.json (0.90 floor:
# the open loop's drain tail is noisier than the closed legs).  The
# r07 leg above stays UNCHANGED and still gates vs BENCH_GATE_r07 —
# that IS the cache-off pure-overlay leg: same programs, byte-
# identical engine (proven bit-identical in tests/test_serve.py).
	python bench.py --mode serve --nodes 16384 --arrival-rate 8000 --duration 3 --serve-slots 1024 --key-pool 1024 --serve-cache 2048 --serve-out /tmp/serve_cache.json
	python -m opendht_tpu.tools.check_trace /tmp/serve_cache.json
	python -m opendht_tpu.tools.check_bench /tmp/serve_cache.json BENCH_GATE_r12.json --min-ratio 0.90
# Round-20 RESIDENT leg: admit -> rounds -> harvest fused into ONE
# device program (ring admission, double-buffered drain — the burst
# loop's per-burst readback is gone).  Same 16k/Zipf/cache shape as
# the r12 leg but offered 10k req/s: the resident engine must sustain
# >= 1.15x the burst row's 7,580 req/s (recorded: 9,550 req/s, p50
# 22.5 ms vs the burst leg's 32 ms) with host orchestration < 5 % of
# the serve wall — check_trace gates the ring conservation identity,
# depth bounds, and the orchestration share against the budget
# RECORDED in the artifact (--resident-orch-budget 0.05), so a
# host-bound regression fails its own file.  The burst legs above are
# UNCHANGED and still gate vs r07/r12 — that is the A/B: same
# workload shape, two engines, both walls recorded every gate run.
	python bench.py --mode serve --nodes 16384 --arrival-rate 10000 --duration 3 --serve-slots 1024 --key-pool 1024 --serve-cache 2048 --serve-engine resident --resident-orch-budget 0.05 --serve-out /tmp/serve_resident.json
	python -m opendht_tpu.tools.check_trace /tmp/serve_resident.json
	python -m opendht_tpu.tools.check_bench /tmp/serve_resident.json BENCH_GATE_r17.json --min-ratio 0.90
# (2) FIRST-CLASS SHARDED serve: the mesh engine (routed per-round
# exchanges, replicated cache) driven open-loop at 65k nodes on the
# 8-device virtual mesh, gated vs BENCH_GATE_r12_sharded.json (0.90
# floor + 2.0x p99 ceiling: collective walls on the virtual CPU mesh
# are spikier than the local engine's).  The closed-loop replay
# bit-identity vs sharded_lookup rides the `test` prerequisite.
	env XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu python bench.py --mode serve --sharded --nodes 65536 --arrival-rate 1000 --duration 3 --serve-slots 1024 --key-pool 1024 --serve-cache 2048 --slo-ms 2000 --serve-out /tmp/serve_sharded.json
	python -m opendht_tpu.tools.check_trace /tmp/serve_sharded.json
	python -m opendht_tpu.tools.check_bench /tmp/serve_sharded.json BENCH_GATE_r12_sharded.json --min-ratio 0.90 --max-p99-ratio 2.0
# (3) OVERLOAD sheds instead of exiting 2: a 20k req/s firehose
# against 256 slots under policy `shed` — the engine must stay up,
# finish, and conserve sheds in the lifecycle plane (check_trace
# proves admitted == completed + in-flight + expired with shed in
# the offered denominator).  Before round 16 this exact leg was a
# guaranteed exit 2.
	python bench.py --mode serve --nodes 16384 --arrival-rate 20000 --duration 2 --serve-slots 256 --key-pool 1024 --serve-cache 1024 --admission shed --admit-rate 2000 --serve-out /tmp/serve_shed.json
	python -m opendht_tpu.tools.check_trace /tmp/serve_shed.json
# (4) The committed 1M-node sharded acceptance artifact is
# re-validated so the record can never rot.
	python -m opendht_tpu.tools.check_trace SERVE_SHARDED_r12.json
	python bench.py --mode crawl --nodes 100000 > /tmp/crawl_row.json
	python -m opendht_tpu.tools.check_bench /tmp/crawl_row.json BENCH_GATE_r08.json
	python bench.py --mode monitor --nodes 16384 --sweeps 3 --kill-frac 0.05 --monitor-out /tmp/monitor.json
	python -m opendht_tpu.tools.check_trace /tmp/monitor.json
	python -m opendht_tpu.tools.check_bench /tmp/monitor.json MONITOR_GATE_r08.json
	python -m opendht_tpu.tools.check_trace MONITOR_r08.json
	python bench.py --mode index --nodes 16384 --entries 512 --key-pool 256 --scans 16 --scan-span 16 --repeat 3 --index-out /tmp/index.json
	python -m opendht_tpu.tools.check_trace /tmp/index.json
	python -m opendht_tpu.tools.check_bench /tmp/index.json BENCH_GATE_r10.json --min-ratio 0.90
# ^ 0.90 rate floor for the index leg only: its timed scan wall is
#   ~1 s (vs 20 s+ on the lookup leg), so run-to-run machine noise is
#   a visibly wider band — measured 6% between back-to-back clean
#   runs.  The exactness gates (recall == 1.0, zero extras, leaf/split
#   conservation) are absolute and unaffected by the looser floor.
	python -m opendht_tpu.tools.check_trace INDEX_r10.json
	python bench.py --mode soak --nodes 16384 --arrival-rate 1500 --duration 5 --serve-slots 1024 --key-pool 1024 --puts 1024 --outage-frac 0.02 --slo-ms 500 --soak-out /tmp/soak.json
	python -m opendht_tpu.tools.check_trace /tmp/soak.json
	python -m opendht_tpu.tools.check_bench /tmp/soak.json BENCH_GATE_r11.json --min-ratio 0.90 --max-p99-ratio 2.0
	python -m opendht_tpu.tools.check_trace SOAK_r11.json
	python bench.py --mode chaos --nodes 16384 --puts 2048
	python bench.py --mode chaos-lookup --nodes 16384 --lookups 4096 --recall-sample 256
# The AUTH leg (round 17): the device integrity plane — content-
# addressed values verified in-jit at store-insert AND get-merge,
# poisoned-value injection (bit-flipped payloads, forged ids, stale
# replays) under 10% churn.  check_trace proves the artifact's exact
# StoreTrace conservation (requests == accepts + rejects +
# integrity_rejects, both arms, every leg), that the defended arm
# accepted ZERO forged rows at integrity exactly 1.0 with the
# undefended arm visibly degraded, that the measured verify overhead
# sits inside the stated <=10% budget, and that every signature figure
# is null (not fabricated) when the optional cryptography dep is
# absent; check_bench re-gates the quality fields against the recorded
# BENCH_GATE_r13.json row.
	python bench.py --mode auth --nodes 16384 --puts 2048 --repeat 3 --auth-out /tmp/auth.json
	python -m opendht_tpu.tools.check_trace /tmp/auth.json
	python -m opendht_tpu.tools.check_bench /tmp/auth.json BENCH_GATE_r13.json
# The CHUNKED leg (round 20): multi-part values on the 8-device
# sharded engine under injected chunk faults — per-part drop masks,
# a mid-announce kill, a higher-seq torn overwrite, and a single
# bit-flipped part at a fresh seq.  check_trace proves the artifact's
# whole-value StoreTrace conservation (summed per-part requests and
# accepts against the whole-value lookup oracle), the torn fail-safe
# (every torn value reads MISSING — zero garbled bytes on any leg of
# either arm), that the defended arm rejected every forged part at
# the get-merge root check (integrity exactly 1.0, root_rejects
# covering every affected row) with the undefended arm visibly
# degraded, and that churn+heal republish sweeps restored every torn
# value; check_bench re-gates the quality fields against the recorded
# BENCH_GATE_r16.json row.
	env XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu python bench.py --mode chunked --nodes 8192 --puts 64 --chunked-out /tmp/chunked.json
	python -m opendht_tpu.tools.check_trace /tmp/chunked.json
	python -m opendht_tpu.tools.check_bench /tmp/chunked.json BENCH_GATE_r16.json

# Profiling workflow (README "Profiling"): the gate-config cost ledger
# with its roofline verdict, plus the small republish-sweep profile —
# everything ROADMAP #1/#4 need before touching the round core or the
# maintenance path again.
profile:
	python bench.py --nodes 100000 --lookups 20000 --repeat 2 --recall-sample 256 --ledger-out /tmp/ledger.json
	python -m opendht_tpu.tools.check_trace /tmp/ledger.json
	python -m opendht_tpu.tools.roofline /tmp/ledger.json
	python bench.py --mode repub-profile --nodes 16384 --puts 2048 --repeat 2 --ledger-out /tmp/ledger_repub.json
	python -m opendht_tpu.tools.check_trace /tmp/ledger_repub.json
	python -m opendht_tpu.tools.roofline /tmp/ledger_repub.json

clean:
	rm -f opendht_tpu/native/libdhtcore-*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
