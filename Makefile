# opendht_tpu build/test entry points (the reference ships CMake +
# autotools + MSVC, ref CMakeLists.txt:17-22; here the Python package is
# the product and the only compiled artifact is the native hot path).

NATIVE_SRC := opendht_tpu/native/dhtcore.cpp

.PHONY: all native test bench clean

all: native

native:
	python -c "from opendht_tpu import native; assert native.available(); print('libdhtcore ready')"

test:
	python -m pytest tests/ -q

bench:
	python bench.py

clean:
	rm -f opendht_tpu/native/libdhtcore-*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
